"""Broker-grade inter-process bus: one ROUTER socket, durable queues.

The distributed-bus role the reference fills with RabbitMQ (publisher
confirms ``rabbitmq_publisher.py:146-149``; manual ack + nack-requeue
``rabbitmq_subscriber.py:504-560``; durable pre-declared queues
``infra/rabbitmq/definitions.json``). Design:

* **One broker socket.** All routing keys multiplex over a single ZMQ
  ROUTER endpoint — no per-key ports, no hash collisions (the round-1
  port-hash topology collided 17 keys into 64 ports). Publishers and
  consumers are DEALER clients doing strict request/reply with timeouts.
* **Durable by default.** Every published envelope lands in a sqlite
  (WAL) queue table before the publisher confirm is sent; a broker crash
  or restart loses nothing. In-flight deliveries carry a lease — if a
  consumer dies mid-message, the lease expires and the message requeues.
* **Ack / nack-requeue / DLQ.** Callback success acks; failure nacks and
  requeues with an attempt count; past ``max_redeliveries`` the message
  parks in the dead-letter state, visible to the failed-queues CLI.
* **At-least-once.** Retries on timeouts can duplicate deliveries; the
  pipeline is idempotent end-to-end (deterministic ids, upserts), same
  contract as the reference's bus.

The broker runs embedded (``Broker.start()`` thread) or standalone:
``python -m copilot_for_consensus_tpu.bus.broker --port 5700 --db q.db``.
"""

from __future__ import annotations

import contextlib
import json
import sqlite3
import threading
import time
from typing import Any

from copilot_for_consensus_tpu.bus.base import (
    BusSaturated,
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PoisonEnvelope,
    PublishError,
)

try:
    import zmq

    HAS_ZMQ = True
except ImportError:  # pragma: no cover - environment without pyzmq
    HAS_ZMQ = False

DEFAULT_PORT = 5700
DEFAULT_LEASE_S = 30.0
# Subscribers that don't set a group share one queue per routing key
# (competing consumers) — the reference's one-durable-queue-per-key
# topology. Distinct groups each get every message (service fan-out).
DEFAULT_GROUP = "default"


class _QueueStore:
    """sqlite-backed message queues. One table, state machine per row:
    pending → inflight → (acked | pending | dead).

    Consumer groups (the AMQP binding model, reference
    ``infra/rabbitmq/definitions.json``): a binding is (routing_key,
    group); publish inserts one row per bound group so distinct groups
    each see every message (service fan-out) while consumers sharing a
    group compete (replicas). Messages published before any binding
    exists are parked (``grp=''``) and handed to the first group that
    binds the key."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.Lock()
        with self._lock, self._db:
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS messages (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    rk TEXT NOT NULL,
                    grp TEXT NOT NULL DEFAULT '',
                    envelope TEXT NOT NULL,
                    state TEXT NOT NULL DEFAULT 'pending',
                    attempts INTEGER NOT NULL DEFAULT 0,
                    lease_expires REAL,
                    enqueued_at REAL NOT NULL,
                    reason TEXT
                )""")
            try:  # pre-group db files: add the column in place
                self._db.execute(
                    "ALTER TABLE messages ADD COLUMN grp TEXT "
                    "NOT NULL DEFAULT ''")
            except sqlite3.OperationalError:
                pass
            try:  # pre-quarantine db files: dead-letter reason column
                self._db.execute(
                    "ALTER TABLE messages ADD COLUMN reason TEXT")
            except sqlite3.OperationalError:
                pass
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS bindings (
                    rk TEXT NOT NULL,
                    grp TEXT NOT NULL,
                    UNIQUE (rk, grp)
                )""")
            self._db.execute(
                "CREATE INDEX IF NOT EXISTS idx_rk_grp_state "
                "ON messages (rk, grp, state, id)")
            # Broker (re)start: whatever was in flight requeues.
            self._db.execute(
                "UPDATE messages SET state='pending', lease_expires=NULL "
                "WHERE state='inflight'")

    def bind(self, rks: list[str], grp: str) -> None:
        with self._lock, self._db:
            for rk in rks:
                self._db.execute(
                    "INSERT OR IGNORE INTO bindings (rk, grp) VALUES (?, ?)",
                    (rk, grp))
                # Parked pre-bind messages go to the first binder.
                self._db.execute(
                    "UPDATE messages SET grp=? "
                    "WHERE rk=? AND grp='' AND state='pending'", (grp, rk))

    def enqueue(self, rk: str, envelope: str) -> tuple[int, int]:
        """Insert one row per bound group; returns (last row id, depth)
        where depth is the key's worst per-group pending count AFTER the
        insert — piggybacked on the publisher confirm so producers get
        backpressure feedback for free with every publish."""
        now = time.time()
        with self._lock, self._db:
            groups = [g for (g,) in self._db.execute(
                "SELECT grp FROM bindings WHERE rk=?", (rk,))]
            last = 0
            for grp in (groups or [""]):
                cur = self._db.execute(
                    "INSERT INTO messages (rk, grp, envelope, enqueued_at) "
                    "VALUES (?, ?, ?, ?)", (rk, grp, envelope, now))
                last = cur.lastrowid
            return last, self._depth_locked(rk)

    def enqueue_many(self, items: list[tuple[str, str]]
                     ) -> dict[str, int]:
        """Grouped publish: every (rk, envelope) lands in ONE locked
        transaction — one sqlite commit and one broker round-trip for
        a whole dispatch wave's follow-up events, instead of one each.
        Returns the post-insert depth per distinct key (the same
        backpressure piggyback as :meth:`enqueue`)."""
        now = time.time()
        with self._lock, self._db:
            groups_of: dict[str, list[str]] = {}
            for rk, envelope in items:
                if rk not in groups_of:
                    groups_of[rk] = [g for (g,) in self._db.execute(
                        "SELECT grp FROM bindings WHERE rk=?", (rk,))]
                for grp in (groups_of[rk] or [""]):
                    self._db.execute(
                        "INSERT INTO messages "
                        "(rk, grp, envelope, enqueued_at) "
                        "VALUES (?, ?, ?, ?)", (rk, grp, envelope, now))
            return {rk: self._depth_locked(rk) for rk in groups_of}

    def _depth_locked(self, rk: str) -> int:
        # Parked rows (grp='', published before any consumer bound —
        # possibly never: report.published and *.failed have no
        # subscriber by design) are retention, not backlog: counting
        # them would make watermark pacing stall a stage forever
        # against a queue nothing drains. Depth = work a LIVE consumer
        # group is behind on.
        row = self._db.execute(
            "SELECT MAX(n) FROM (SELECT COUNT(*) AS n FROM messages "
            "WHERE rk=? AND state='pending' AND grp != '' GROUP BY grp)",
            (rk,)).fetchone()
        return int(row[0] or 0)

    def depth(self, rk: str) -> int:
        """Worst per-group pending count for one key — the watermark
        poll the pacing publisher uses between confirms."""
        with self._lock:
            return self._depth_locked(rk)

    def fetch(self, rks: list[str], grp: str, limit: int, lease_s: float
              ) -> list[tuple[int, str, str, int]]:
        """Atomically move up to ``limit`` pending messages (across the
        given keys, within one group) to inflight. Returns
        (id, rk, envelope, attempts)."""
        now = time.time()
        qmarks = ",".join("?" for _ in rks)
        with self._lock, self._db:
            rows = self._db.execute(
                f"SELECT id, rk, envelope, attempts FROM messages "
                f"WHERE state='pending' AND grp=? AND rk IN ({qmarks}) "
                f"ORDER BY id LIMIT ?", (grp, *rks, limit)).fetchall()
            if rows:
                ids = [r[0] for r in rows]
                self._db.execute(
                    f"UPDATE messages SET state='inflight', "
                    f"lease_expires=? WHERE id IN "
                    f"({','.join('?' for _ in ids)})",
                    (now + lease_s, *ids))
            return rows

    def ack(self, ids: list[int]) -> None:
        if not ids:
            return
        with self._lock, self._db:
            self._db.execute(
                f"DELETE FROM messages WHERE id IN "
                f"({','.join('?' for _ in ids)}) AND state='inflight'",
                ids)

    def nack(self, ids: list[int], max_redeliveries: int,
             poison: bool = False, reason: str | None = None) -> None:
        if not ids:
            return
        qmarks = ",".join("?" for _ in ids)
        with self._lock, self._db:
            if poison:
                # Quarantine: a deterministically-unprocessable message
                # (schema-invalid, non-retryable handler error) skips
                # the redelivery budget entirely — straight to the
                # dead-letter state with a structured reason, attempts
                # untouched so the operator sees it never cycled.
                self._db.execute(
                    f"UPDATE messages SET state='dead', "
                    f"lease_expires=NULL, reason=? "
                    f"WHERE id IN ({qmarks}) AND state='inflight'",
                    (reason or "poison", *ids))
                return
            self._db.execute(
                f"UPDATE messages SET attempts=attempts+1, "
                f"lease_expires=NULL, state=CASE WHEN attempts+1 >= ? "
                f"THEN 'dead' ELSE 'pending' END, "
                f"reason=CASE WHEN attempts+1 >= ? THEN ? ELSE reason END "
                f"WHERE id IN ({qmarks}) AND state='inflight'",
                (max_redeliveries, max_redeliveries,
                 reason or "redelivery budget exhausted", *ids))

    def expire_leases(self, parked_ttl_s: float = 300.0) -> int:
        with self._lock, self._db:
            cur = self._db.execute(
                "UPDATE messages SET state='pending', lease_expires=NULL "
                "WHERE state='inflight' AND lease_expires < ?",
                (time.time(),))
            # Parked rows (published with no binding) exist only to cover
            # the startup race where a subscriber binds moments later; a
            # key nothing ever binds (e.g. a terminal event with no
            # consumer) must not grow the db forever — AMQP drops
            # unroutable messages outright, we just do it on a delay.
            self._db.execute(
                "DELETE FROM messages WHERE grp='' AND state='pending' "
                "AND enqueued_at < ?", (time.time() - parked_ttl_s,))
            return cur.rowcount

    def counts(self) -> dict[str, dict[str, int]]:
        """Per-key state split. Pre-bind retention rows surface as
        ``parked`` (not ``pending``): no live consumer group owes that
        work, so backpressure (watermark pacing, the ingestion pacer)
        and the queue-depth gauges/alerts must not count it as
        backlog. ``pending`` is the WORST single consumer group's
        backlog (same semantics as :meth:`depth` and the
        ``copilot_bus_pending`` gauge the 1000-message SLO alerts on) —
        summing across groups would inflate a 4-consumer key 4x past
        the depth any one consumer actually owes. Other states sum
        across groups."""
        with self._lock:
            rows = self._db.execute(
                "SELECT rk, CASE WHEN grp='' AND state='pending' "
                "THEN 'parked' ELSE state END AS st, grp, COUNT(*) "
                "FROM messages GROUP BY rk, st, grp").fetchall()
        out: dict[str, dict[str, int]] = {}
        for rk, state, _grp, n in rows:
            states = out.setdefault(rk, {})
            if state == "pending":
                states[state] = max(states.get(state, 0), n)
            else:
                states[state] = states.get(state, 0) + n
        return out

    def dead_letters(self, rk: str | None = None
                     ) -> list[tuple[int, str, str, int, str]]:
        q = ("SELECT id, rk, envelope, attempts, "
             "COALESCE(reason, '') FROM messages WHERE state='dead'")
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock:
            return self._db.execute(q + " ORDER BY id", args).fetchall()

    def requeue_dead(self, rk: str | None = None) -> int:
        q = "UPDATE messages SET state='pending', attempts=0, " \
            "reason=NULL WHERE state='dead'"
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock, self._db:
            return self._db.execute(q, args).rowcount

    def purge_dead(self, rk: str | None = None) -> int:
        q = "DELETE FROM messages WHERE state='dead'"
        args: tuple = ()
        if rk:
            q += " AND rk=?"
            args = (rk,)
        with self._lock, self._db:
            return self._db.execute(q, args).rowcount

    def close(self) -> None:
        with self._lock:
            self._db.close()


class Broker:
    """The broker process: ROUTER socket + durable queue store."""

    def __init__(self, port: int = DEFAULT_PORT, db_path: str = ":memory:",
                 host: str = "127.0.0.1", max_redeliveries: int = 3,
                 lease_s: float = DEFAULT_LEASE_S,
                 expire_interval_s: float = 1.0):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        self.host = host
        self.port = port
        self.store = _QueueStore(db_path)
        self.max_redeliveries = max_redeliveries
        self.lease_s = lease_s
        # Lease-expiry sweep cadence: the sweep used to run on EVERY
        # fetch, fine with one consumer per stage but a broker-loop
        # saturator under worker pools (N workers × 20 idle polls/s
        # each = hundreds of full-table parked-row scans per second on
        # the single request thread — counts()/depth() clients then
        # time out). Expiry only needs lease granularity (30 s), so
        # once a second is already 30× finer than required.
        self.expire_interval_s = expire_interval_s
        self._last_expire = 0.0   # only touched on the run() thread
        self._ctx = zmq.Context.instance()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._bound = threading.Event()

    # ---- request handling -------------------------------------------

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        if op == "pub":
            mid, depth = self.store.enqueue(req["rk"],
                                            json.dumps(req["envelope"]))
            # publisher confirm + the key's pending depth, so every
            # producer gets backpressure feedback with its confirm
            return {"ok": True, "id": mid, "depth": depth}
        if op == "pub_batch":
            depths = self.store.enqueue_many(
                [(it["rk"], json.dumps(it["envelope"]))
                 for it in req.get("items", [])])
            return {"ok": True, "n": len(req.get("items", [])),
                    "depths": depths}
        if op == "depth":
            return {"ok": True, "depth": self.store.depth(req["rk"])}
        if op == "bind":
            self.store.bind(list(req.get("rks", [])),
                            req.get("group", DEFAULT_GROUP))
            return {"ok": True}
        if op == "fetch":
            # sweep cadence tracks the lease: a test broker with a
            # 50 ms lease sweeps (nearly) every fetch, the production
            # 30 s lease sweeps at most once a second
            now = time.time()
            if now - self._last_expire >= min(self.expire_interval_s,
                                              self.lease_s / 2):
                self._last_expire = now
                self.store.expire_leases()
            rows = self.store.fetch(req["rks"],
                                    req.get("group", DEFAULT_GROUP),
                                    int(req.get("max", 16)), self.lease_s)
            return {"ok": True, "msgs": [
                {"id": i, "rk": rk, "envelope": json.loads(env),
                 "attempts": at} for i, rk, env, at in rows]}
        if op == "ack":
            self.store.ack(list(req.get("ids", [])))
            return {"ok": True}
        if op == "nack":
            self.store.nack(list(req.get("ids", [])), self.max_redeliveries,
                            poison=bool(req.get("poison")),
                            reason=req.get("reason"))
            return {"ok": True}
        if op == "counts":
            return {"ok": True, "counts": self.store.counts()}
        if op == "dead":
            return {"ok": True, "msgs": [
                {"id": i, "rk": rk, "envelope": json.loads(env),
                 "attempts": at, "reason": reason}
                for i, rk, env, at, reason in self.store.dead_letters(
                    req.get("rk"))]}
        if op == "requeue_dead":
            return {"ok": True, "n": self.store.requeue_dead(req.get("rk"))}
        if op == "purge_dead":
            return {"ok": True, "n": self.store.purge_dead(req.get("rk"))}
        if op == "ping":
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    # ---- run loop ----------------------------------------------------

    def run(self) -> None:
        sock = self._ctx.socket(zmq.ROUTER)
        sock.setsockopt(zmq.LINGER, 0)
        if self.port == 0:
            self.port = sock.bind_to_random_port(f"tcp://{self.host}")
        else:
            # A broker restarting right after a crash can race the old
            # socket's TIME_WAIT; retry instead of dying on EADDRINUSE.
            # Deadline stays under start()'s _bound.wait(5) so a failed
            # bind surfaces there rather than binding after the caller
            # already gave up. Non-transient errnos re-raise immediately.
            deadline = time.time() + 4
            while True:
                try:
                    sock.bind(f"tcp://{self.host}:{self.port}")
                    break
                except zmq.ZMQError as exc:
                    if exc.errno != zmq.EADDRINUSE or time.time() > deadline:
                        raise
                    # stop-aware backoff: a broker stopped while waiting
                    # out TIME_WAIT must exit, not finish the bind retry
                    if self._stop.wait(0.2):
                        sock.close()
                        return
        self._bound.set()
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        try:
            while not self._stop.is_set():
                if not dict(poller.poll(timeout=100)):
                    continue
                frames = sock.recv_multipart()
                identity, payload = frames[0], frames[-1]
                try:
                    reply = self._handle(json.loads(payload))
                except Exception as exc:   # malformed request
                    reply = {"ok": False, "error": str(exc)}
                sock.send_multipart(
                    [identity, b"", json.dumps(reply).encode()])
        finally:
            sock.close()

    def start(self) -> "Broker":
        self._thread = threading.Thread(target=self.run, name="bus-broker",
                                        daemon=True)
        self._thread.start()
        if not self._bound.wait(timeout=5):
            raise PublishError("broker failed to bind")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.store.close()

    @property
    def address(self) -> str:
        return f"tcp://{self.host}:{self.port}"


class _Client:
    """One DEALER connection doing strict request/reply with timeouts."""

    def __init__(self, address: str, timeout_ms: int = 5000,
                 retries: int = 3):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        self.address = address
        self.timeout_ms = timeout_ms
        self.retries = retries
        self._ctx = zmq.Context.instance()
        self._sock = None
        self._lock = threading.Lock()

    def _connect(self):
        if self._sock is not None:
            self._sock.close(linger=0)
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(self.address)

    def request(self, req: dict) -> dict:
        """Send one request, await the reply. Times out → reconnect and
        retry (at-least-once: a retried 'pub' may duplicate; consumers
        are idempotent by pipeline contract)."""
        with self._lock:
            if self._sock is None:
                self._connect()
            payload = json.dumps(req).encode()
            last = "no attempt made"
            for attempt in range(1, max(1, self.retries) + 1):
                self._sock.send_multipart([b"", payload])
                poller = zmq.Poller()
                poller.register(self._sock, zmq.POLLIN)
                if dict(poller.poll(timeout=self.timeout_ms)):
                    frames = self._sock.recv_multipart()
                    reply = json.loads(frames[-1])
                    if not reply.get("ok"):
                        raise PublishError(reply.get("error", "broker nak"))
                    return reply
                last = (f"timeout after {self.timeout_ms}ms on attempt "
                        f"{attempt}/{self.retries}")
                self._connect()      # stale socket: drop + reconnect
            raise PublishError(f"broker unreachable at {self.address} "
                               f"({last})")

    def close(self):
        with self._lock:
            if self._sock is not None:
                self._sock.close(linger=0)
                self._sock = None


class _Outbox:
    """Bounded durable publish outbox: envelopes the broker could not
    confirm park here (sqlite WAL, same file discipline as
    ``_QueueStore``; ``:memory:`` for embedded publishers — set
    ``outbox_path`` when parked work must survive a publisher-process
    restart too). Strictly FIFO: rows leave only after the broker
    confirmed them, so replay order == publish order."""

    def __init__(self, path: str = ":memory:", cap: int = 10000):
        self.cap = cap
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.Lock()
        with self._lock, self._db:
            self._db.execute("""
                CREATE TABLE IF NOT EXISTS outbox (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    rk TEXT NOT NULL,
                    envelope TEXT NOT NULL,
                    parked_at REAL NOT NULL
                )""")
            # cached row count (seeded from durable files): depth() is
            # on the publish hot path, where it almost always answers
            # "empty" — that must not cost a sqlite query per publish
            self._n = int(self._db.execute(
                "SELECT COUNT(*) FROM outbox").fetchone()[0])

    def depth(self) -> int:
        with self._lock:
            return self._n

    def append(self, rk: str, envelope_json: str) -> int:
        with self._lock, self._db:
            cur = self._db.execute(
                "INSERT INTO outbox (rk, envelope, parked_at) "
                "VALUES (?, ?, ?)", (rk, envelope_json, time.time()))
            self._n += 1
            return cur.lastrowid

    def oldest(self, limit: int) -> list[tuple[int, str, str]]:
        with self._lock:
            return self._db.execute(
                "SELECT id, rk, envelope FROM outbox ORDER BY id "
                "LIMIT ?", (limit,)).fetchall()

    def remove(self, ids: list[int]) -> None:
        if not ids:
            return
        with self._lock, self._db:
            cur = self._db.execute(
                f"DELETE FROM outbox WHERE id IN "
                f"({','.join('?' for _ in ids)})", ids)
            self._n -= cur.rowcount

    def close(self) -> None:
        with self._lock:
            self._db.close()


class BrokerPublisher(EventPublisher):
    """Publishes with broker confirms (the role of RabbitMQ publisher
    confirms, ``rabbitmq_publisher.py:146-149``) — and, new in the
    pipeline fault plane round, SURVIVES the broker being away:

    * **Outage ride-through.** A publish the broker cannot confirm
      parks in a bounded durable outbox instead of raising into the
      handler (where it used to become nack → redelivery → dead
      letter); a stop-aware backoff thread replays parked envelopes in
      publish order once the broker is back, so a broker restart costs
      latency, not work. Outbox overflow raises the structured
      :class:`BusSaturated` (``reason="outbox-full"``) — honest
      backpressure, never a silent drop.
    * **Depth-watermark backpressure.** Every confirm carries the
      routing key's broker-side pending depth. With
      ``high_watermark`` configured, a publish that lands at/above it
      blocks (stop-aware, bounded by ``saturation_max_wait_s``) until
      the key drains below ``low_watermark`` — pacing the producer at
      the source — and ``saturation()`` exposes the hot keys so
      services can throttle their own consumption too.
    * **Fault plane.** ``faults`` (a ``bus/faults.py`` boundary or
      plan) fires the ``publish`` boundary: injected faults take the
      exact outage path above, which is how the chaos harness proves
      the ride-through deterministically.

    Config keys: ``timeout_ms``, ``retries``, ``outbox_path``,
    ``outbox_cap``, ``high_watermark`` (0 = off), ``low_watermark``
    (default half of high), ``saturation_poll_s``,
    ``saturation_max_wait_s``."""

    def __init__(self, config: Any = None, client=None, faults=None):
        from copilot_for_consensus_tpu.bus.faults import resolve_boundary

        cfg = dict(config or {})
        self._address = cfg.get("address") or (
            f"tcp://{cfg.get('host', '127.0.0.1')}:"
            f"{cfg.get('port', DEFAULT_PORT)}")
        self._client = client if client is not None else _Client(
            self._address, timeout_ms=int(cfg.get("timeout_ms", 5000)),
            retries=int(cfg.get("retries", 3)))
        self._depth_client = None  # lazy single-try client (pacing polls)
        self.high_watermark = int(cfg.get("high_watermark", 0) or 0)
        self.low_watermark = int(
            cfg.get("low_watermark", max(1, self.high_watermark // 2)))
        self.saturation_poll_s = float(cfg.get("saturation_poll_s", 0.05))
        # Pace bound: must stay WELL below the broker lease
        # (DEFAULT_LEASE_S, 30s) — a pace can run inside a consumer
        # handler that is itself holding a lease, and blocking past it
        # turns backpressure into lease-expiry redeliveries (duplicate
        # work) exactly when the bus is already saturated.
        self.saturation_max_wait_s = float(
            cfg.get("saturation_max_wait_s", 10.0))
        # How stale a last-confirm depth snapshot may be before
        # saturation() re-polls the broker for that key: without a
        # refresh, a key hot at its last publish would read saturated
        # forever once the producer goes quiet, throttling every
        # service until process restart.
        self.saturation_refresh_s = float(
            cfg.get("saturation_refresh_s", 1.0))
        self.outbox = _Outbox(cfg.get("outbox_path", ":memory:"),
                              cap=int(cfg.get("outbox_cap", 10000)))
        self.faults = resolve_boundary(faults)
        #: rk -> (last known pending depth, monotonic stamp)
        self._depths: dict[str, tuple[int, float]] = {}
        # publish-window buffer (grouped publishes): THREAD-local —
        # a service's worker pool shares one publisher, and each
        # worker's wave must flush only its own buffered follow-ups
        self._window = threading.local()
        self._stop = threading.Event()
        self._replay_lock = threading.Lock()
        self._replayer: threading.Thread | None = None
        self._stats_lock = threading.Lock()
        self._stats = {"confirmed": 0, "parked": 0, "replayed": 0,
                       "overflow": 0, "throttle_waits": 0}

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += n

    # ---- publish path ------------------------------------------------

    def publish_envelope(self, envelope, routing_key=None):
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        from copilot_for_consensus_tpu.obs import trace

        # Trace-context propagation (obs/trace.py): stamp once at first
        # publish; an envelope that already carries a trace_id (outbox
        # replay, DLQ/startup requeue) keeps it, so at-least-once
        # delivery never orphans a trace.
        env = dict(trace.inject(envelope, routing_key))
        buf = getattr(self._window, "buf", None)
        if buf is not None:
            # Inside a publish window (batched wave dispatch): buffer —
            # the publish span is already recorded with the correct
            # per-envelope parent above; the broker sees the whole
            # window as ONE pub_batch request at flush.
            buf.append((routing_key, env))
            return
        outage: BaseException | None = None
        if self.faults is not None:
            try:
                self.faults.check("publish")
            except Exception as exc:  # injected fault == broker outage
                outage = exc
        # Ordering: while anything is parked, new publishes park BEHIND
        # it — rows leave the outbox only after their confirm, so the
        # per-publisher order survives the outage.
        if outage is None and self.outbox.depth() == 0:
            try:
                reply = self._client.request(
                    {"op": "pub", "rk": routing_key, "envelope": env})
            except PublishError as exc:
                outage = exc
            else:
                self._bump("confirmed")
                self._pace(routing_key, int(reply.get("depth", 0)))
                return
        self._park(routing_key, env, outage)

    @contextlib.contextmanager
    def publish_window(self):
        """Grouped publishes for one batched dispatch: every
        ``publish`` inside the window buffers (spans and trace stamps
        recorded immediately, with their real per-envelope parents)
        and the window exit sends ONE ``pub_batch`` broker request —
        one round-trip and one broker-side transaction for the wave's
        whole follow-up fan-out. Reentrant-safe per thread (an inner
        window joins the outer one); the outage path parks the whole
        buffer in the outbox in order, so ride-through semantics are
        identical to per-publish. Raises :class:`BusSaturated` only
        when the outbox overflows — the caller (wave dispatch) nacks
        the wave and redelivery regenerates the publishes."""
        outer = getattr(self._window, "buf", None)
        if outer is not None:
            yield          # nested: the outer window owns the flush
            return
        buf: list[tuple[str, dict]] = []
        self._window.buf = buf
        try:
            yield
        finally:
            # flush even when the body raised: envelopes whose
            # finishers already succeeded are about to be acked — their
            # follow-ups must reach the broker (or the outbox)
            self._window.buf = None
            self._flush_window(buf)

    def _flush_window(self, buf: list[tuple[str, dict]]) -> None:
        if not buf:
            return
        outage: BaseException | None = None
        if self.faults is not None:
            try:
                # one boundary fire per flush: the wave pays one
                # publish round-trip, so it offers one fault window
                self.faults.check("publish")
            except Exception as exc:
                outage = exc
        # Sub-batch cap: an UNBOUNDED pub_batch would land a whole
        # wave's fan-out past the watermark before pacing could see it
        # (the overload arm measured depth = wave size, not watermark).
        # Capping each broker request at HALF the watermark restores
        # pacing granularity — worst transient = existing backlog (hw,
        # where pacing engages) + one sub-batch (hw/2) = 1.5×hw,
        # strictly inside the 2×hw depth SLO the watermark is sized
        # against — while an unwatermarked publisher still gets
        # bounded transactions.
        cap = max(1, self.high_watermark // 2) \
            if self.high_watermark > 0 else 128
        start = 0
        while outage is None and start < len(buf):
            if self.outbox.depth() > 0:
                break                   # park behind the backlog
            chunk = buf[start:start + cap]
            try:
                reply = self._client.request({
                    "op": "pub_batch",
                    "items": [{"rk": rk, "envelope": env}
                              for rk, env in chunk]})
            except PublishError as exc:
                outage = exc
                break
            start += len(chunk)
            self._bump("confirmed", len(chunk))
            depths = {rk: int(d) for rk, d in
                      (reply.get("depths") or {}).items()}
            for rk, d in depths.items():
                self._note_depth(rk, d)
            for rk, d in depths.items():
                if self.high_watermark and d >= self.high_watermark:
                    # one pace against the hottest key is enough:
                    # _pace re-polls until IT drains, which bounds
                    # the producer exactly like per-publish pacing
                    self._pace(rk, d)
                    break
        # Broker away (or injected fault): park the window's REMAINDER
        # in publish order — the replay thread preserves FIFO, so the
        # ride-through contract is unchanged under grouping. If the
        # outbox hits its cap mid-park, the un-parked tail cannot go
        # anywhere: count every dropped envelope as overflow (visible
        # in outbox_stats) and raise the structured BusSaturated — the
        # wave dispatch nacks its envelopes on this raise, and
        # redelivery regenerates ALL the wave's publishes (the parked
        # portion's replay duplicates are absorbed by idempotent ids).
        remainder = buf[start:]
        for k, (rk, env) in enumerate(remainder):
            try:
                self._park(rk, env, outage)
            except BusSaturated:
                dropped = len(remainder) - k
                if dropped > 1:        # _park counted the first one
                    self._bump("overflow", dropped - 1)
                raise

    def _park(self, routing_key: str, env: dict,
              cause: BaseException | None) -> None:
        with self._replay_lock:
            depth = self.outbox.depth()
            if depth >= self.outbox.cap:
                self._bump("overflow")
                raise BusSaturated(
                    f"publish outbox full ({depth} envelopes parked, "
                    f"cap {self.outbox.cap}) while the broker is "
                    f"unreachable" + (f": {cause}" if cause else ""),
                    routing_key=routing_key, depth=depth,
                    limit=self.outbox.cap, reason="outbox-full")
            self.outbox.append(routing_key, json.dumps(env))
            self._bump("parked")
            self._ensure_replayer()

    def _ensure_replayer(self) -> None:
        # caller holds _replay_lock
        if self._replayer is not None and self._replayer.is_alive():
            return
        self._replayer = threading.Thread(
            target=self._replay_loop, name="bus-publish-replay",
            daemon=True)
        self._replayer.start()

    def _replay_loop(self) -> None:
        """Drain the outbox oldest-first once the broker confirms again.
        Stop-aware exponential backoff between failed rounds (never a
        bare sleep — the jaxlint ``blocking-call`` contract); exits
        when the outbox is empty (re-spawned by the next park)."""
        backoff = 0.1
        while not self._stop.is_set():
            try:
                batch = self.outbox.oldest(16)
                if not batch:
                    with self._replay_lock:
                        if self.outbox.depth() == 0:
                            self._replayer = None
                            return
                    continue
                sent: list[int] = []
                try:
                    for oid, rk, env_json in batch:
                        if self.faults is not None:
                            self.faults.check("publish")
                        reply = self._client.request(
                            {"op": "pub", "rk": rk,
                             "envelope": json.loads(env_json)})
                        sent.append(oid)
                        self._note_depth(rk, int(reply.get("depth", 0)))
                except Exception:  # broker still away (or injected fault)
                    pass
                finally:
                    if sent:
                        self.outbox.remove(sent)
                        self._bump("replayed", len(sent))
            except Exception:
                # close() raced us past its join timeout and shut the
                # outbox db (sqlite ProgrammingError) — or some other
                # infra failure. Durable rows confirmed but not removed
                # replay again next start: at-least-once, absorbed by
                # the idempotent-ids contract.
                if self._stop.is_set():
                    return
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 2.0)
                continue
            if sent:
                backoff = 0.1           # broker is back: full speed
            elif self._stop.wait(backoff):
                return
            else:
                backoff = min(backoff * 2, 2.0)

    # ---- backpressure ------------------------------------------------

    def _note_depth(self, rk: str, depth: int) -> None:
        self._depths[rk] = (int(depth), time.monotonic())

    def _poll_depth(self, rk: str) -> int | None:
        """One best-effort broker depth query (single try, short
        timeout); None when the broker is unreachable."""
        if self._depth_client is None:
            self._depth_client = _Client(self._address,
                                         timeout_ms=1500, retries=1)
        try:
            depth = int(self._depth_client.request(
                {"op": "depth", "rk": rk})["depth"])
        except PublishError:
            return None
        self._note_depth(rk, depth)
        return depth

    def _pace(self, rk: str, depth: int) -> None:
        self._note_depth(rk, depth)
        if not self.high_watermark or depth < self.high_watermark:
            return
        # Saturated: hold THIS producer (stop-aware, bounded) until the
        # key drains below the low watermark — backpressure lands where
        # the flood originates instead of 4x past the SLO downstream.
        self._bump("throttle_waits")
        deadline = time.monotonic() + self.saturation_max_wait_s
        while time.monotonic() < deadline:
            if self._stop.wait(self.saturation_poll_s):
                break
            cur = self._poll_depth(rk)
            if cur is None:
                break       # outage mid-pace: the outbox takes over
            if cur < self.low_watermark:
                break

    def saturation(self) -> dict[str, int]:
        if not self.high_watermark:
            return {}
        hot: dict[str, int] = {}
        now = time.monotonic()
        for rk, (depth, at) in list(self._depths.items()):
            if depth < self.high_watermark:
                continue
            if now - at >= self.saturation_refresh_s:
                # Stale snapshot: the key was hot at its last confirm
                # but the producer has gone quiet since — re-poll so a
                # drained queue stops throttling consumers. Broker
                # unreachable reads as not-hot: the outbox ride-through
                # governs outages, not the consumption throttle.
                refreshed = self._poll_depth(rk)
                if refreshed is None:
                    continue
                depth = refreshed
            if depth >= self.high_watermark:
                hot[rk] = depth
        return hot

    def pending_depths(self) -> dict[str, int]:
        if self._depth_client is None:
            self._depth_client = _Client(self._address,
                                         timeout_ms=1500, retries=1)
        try:
            counts = self._depth_client.request({"op": "counts"})["counts"]
        except PublishError:
            return {}
        return {rk: states.get("pending", 0)
                for rk, states in counts.items()}

    def outbox_stats(self) -> dict[str, int]:
        with self._stats_lock:
            out = dict(self._stats)
        out["outbox_depth"] = self.outbox.depth()
        return out

    def close(self):
        self._stop.set()
        with self._replay_lock:
            replayer = self._replayer
        if replayer is not None:
            # A replayer mid-request against an unreachable broker can
            # block for the client's full retry budget before it sees
            # the stop flag — wait at least that long so the outbox db
            # closes after the thread is really done (the loop also
            # survives a lost race, exiting on the first closed-db
            # error once stop is set).
            budget = max(5.0,
                         getattr(self._client, "timeout_ms", 5000)
                         / 1000.0
                         * max(1, getattr(self._client, "retries", 3))
                         + 1.0)
            replayer.join(timeout=budget)
        self._client.close()
        if self._depth_client is not None:
            self._depth_client.close()
        self.outbox.close()


class BrokerSubscriber(EventSubscriber):
    """Pull-based consumer: fetch → dispatch → ack/nack per message.
    ``group`` names this consumer's queue group: subscribers sharing a
    group compete (replicas), distinct groups each see every message
    (distinct services) — same contract as ``InProcSubscriber``.

    Failure classification (the poison-quarantine contract,
    docs/RESILIENCE.md): a handler raising ``RetryableError`` (or any
    bus-level ``PublishError``) nacks onto the normal lease/redelivery
    path; ``PoisonEnvelope`` or any other exception — a deterministic
    failure redelivery cannot fix — quarantines straight to the
    dead-letter table with a structured reason, skipping the
    redelivery budget. Every failure is logged with routing key +
    event id and counted in ``copilot_bus_dispatch_failures_total``."""

    def __init__(self, config: Any = None, group: str | None = None,
                 client=None, faults=None):
        from copilot_for_consensus_tpu.bus.faults import resolve_boundary
        from copilot_for_consensus_tpu.obs.logging import get_logger
        from copilot_for_consensus_tpu.obs.metrics import NoopMetrics

        cfg = dict(config or {})
        address = cfg.get("address") or (
            f"tcp://{cfg.get('host', '127.0.0.1')}:"
            f"{cfg.get('port', DEFAULT_PORT)}")
        self._address = address
        self._timeout_ms = int(cfg.get("timeout_ms", 5000))
        self._retries = int(cfg.get("retries", 3))
        self._client = client if client is not None else _Client(
            address, timeout_ms=self._timeout_ms, retries=self._retries)
        self.poll_interval_s = float(cfg.get("poll_interval_s", 0.05))
        # Prefetch: how many envelopes one fetch leases (the broker-side
        # `max`). `prefetch` is the config-surface name (`bus.prefetch`,
        # plumbed per service by the runner so pool sizing and prefetch
        # tune together); `batch` kept as the legacy alias.
        self.batch = int(cfg.get("prefetch", cfg.get("batch", 16)))
        self.group = group or cfg.get("group") or DEFAULT_GROUP
        self.faults = resolve_boundary(faults)
        #: shared with the owning pipeline's collector by the runner
        self.metrics = NoopMetrics()
        self.logger = get_logger()
        self._routes: dict[str, EventCallback] = {}
        self._batch_routes: dict[str, Any] = {}
        self._counts_client: _Client | None = None
        self._stop = threading.Event()
        #: (rk, what, started_at) of the in-progress handler dispatch
        #: — what StageWorkerPool.stop() names when this consumer's
        #: worker thread fails to join. Written only by the consume
        #: thread; other threads take a stale-tolerant snapshot read
        #: (GIL-atomic tuple swap, the azure_monitor counter pattern).
        self._current: tuple | None = None

    def subscribe(self, routing_keys, callback):
        for rk in routing_keys:
            self._routes[rk] = callback
        self._client.request({"op": "bind", "rks": list(routing_keys),
                              "group": self.group})

    def subscribe_batch(self, routing_keys, callback) -> bool:
        """Register a wave callback (``bus/base.py:BatchEventCallback``)
        for keys already subscribed via :meth:`subscribe`: a fetch wave
        of same-key envelopes dispatches as ONE callback call with
        grouped ack/nack round-trips; keys without a batch route (and
        wave-level callback failures) keep exact per-envelope
        semantics."""
        for rk in routing_keys:
            self._batch_routes[rk] = callback
        return True

    def counts(self, timeout_ms: int | None = None
               ) -> dict[str, dict[str, int]]:
        """Broker-side per-key state counts (pending/inflight/dead) — the
        ops introspection surface for gauges and the failed-queues CLI.
        ``timeout_ms`` uses a dedicated single-try client so metric
        scrapes fail fast during a broker outage instead of tying up the
        HTTP worker for the full retry budget."""
        if timeout_ms is None:
            return self._client.request({"op": "counts"})["counts"]
        if self._counts_client is None:
            self._counts_client = _Client(self._address,
                                          timeout_ms=timeout_ms, retries=1)
        return self._counts_client.request({"op": "counts"})["counts"]

    def _classify_failure(self, msg: dict, exc: BaseException) -> dict:
        """Map a handler exception to the broker verdict, logging and
        counting it (``bus/broker.py:476`` used to swallow these into a
        bare ``ok = False`` — a redelivery storm with no diagnosis)."""
        from copilot_for_consensus_tpu.core.retry import RetryableError

        envelope = msg.get("envelope") or {}
        transient = isinstance(exc, (RetryableError, PublishError)) \
            and not isinstance(exc, PoisonEnvelope)
        kind = "transient" if transient else "poison"
        # correlation_id + trace_id ride the failure log line (and the
        # dead-letter row keeps the whole envelope), so an operator can
        # pull the trace for a quarantined envelope straight from the
        # copilot_bus_dispatch_failures_total diagnosis.
        data = envelope.get("data") or {}
        tctx = envelope.get("trace") or {}
        self.logger.error(
            "bus dispatch failed",
            routing_key=msg["rk"], group=self.group, kind=kind,
            event_id=envelope.get("event_id", ""),
            event_type=envelope.get("event_type", ""),
            correlation_id=data.get("correlation_id", ""),
            trace_id=tctx.get("trace_id", ""),
            attempts=msg.get("attempts", 0),
            error=str(exc), error_type=type(exc).__name__)
        self.metrics.increment("bus_dispatch_failures_total",
                               labels={"queue": msg["rk"], "kind": kind})
        if transient:
            return {"op": "nack", "ids": [msg["id"]]}
        reason = (exc.reason if isinstance(exc, PoisonEnvelope)
                  else f"{type(exc).__name__}: {exc}")
        self.metrics.increment("bus_poison_total",
                               labels={"queue": msg["rk"]})
        return {"op": "nack", "ids": [msg["id"]], "poison": True,
                "reason": reason[:500]}

    def current_dispatch(self) -> str | None:
        """Human-readable description of the in-progress handler
        dispatch (None when idle) — the stuck-worker diagnostic
        ``StageWorkerPool.stop()`` logs on a join timeout."""
        cur = self._current
        if cur is None:
            return None
        rk, what, t0 = cur
        return f"{rk} {what} ({time.monotonic() - t0:.1f}s)"

    def _dispatch(self, msg: dict) -> None:
        from copilot_for_consensus_tpu.obs import trace

        cb = self._routes.get(msg["rk"])
        verdict = {"op": "ack", "ids": [msg["id"]]}
        if cb is not None:
            # broker-side redelivery count → trace attempt annotation,
            # so a retried delivery's stage span says so
            trace.annotate_delivery(msg["envelope"],
                                    int(msg.get("attempts", 0)))
            self._current = (msg["rk"], f"id={msg['id']}",
                             time.monotonic())
            try:
                cb(msg["envelope"])
            except Exception as exc:
                verdict = self._classify_failure(msg, exc)
            finally:
                self._current = None
        if self.faults is not None:
            try:
                self.faults.check("ack")
            except Exception:
                # Injected ack fault == consumer died before acking:
                # the lease expires and the message redelivers — the
                # at-least-once path the idempotent handlers absorb.
                return
        try:
            self._client.request(verdict)
        except PublishError:
            # Broker unreachable: the lease will expire and the message
            # redelivers — at-least-once holds without us crashing.
            pass

    def _settle(self, acks: list[int],
                nacks: list[tuple[dict, BaseException]]) -> None:
        """Grouped verdict round-trips for a dispatched wave: ONE ack
        request for every success (the broker ack op takes an id list),
        one nack per distinct classification. The injected ``ack``
        fault covers the whole wave — a consumer crash before settling
        loses every verdict at once, exactly like the real failure."""
        if self.faults is not None:
            try:
                self.faults.check("ack")
            except Exception:
                # consumer died before acking: leases expire, the wave
                # redelivers — at-least-once, absorbed by idempotency
                return
        verdicts: list[dict] = []
        if acks:
            verdicts.append({"op": "ack", "ids": acks})
        transient: list[int] = []
        for m, exc in nacks:
            v = self._classify_failure(m, exc)
            if v.get("poison"):
                verdicts.append(v)
            else:
                transient.extend(v["ids"])
        if transient:
            verdicts.append({"op": "nack", "ids": transient})
        for v in verdicts:
            try:
                self._client.request(v)
            except PublishError:
                # Broker unreachable: leases expire and redeliver.
                pass

    def _dispatch_batch(self, rk: str, msgs: list[dict]) -> None:
        """One wave, one callback call, grouped settle. A wave-level
        callback raise falls back to per-envelope dispatch so a single
        bad message degrades to the exact single-dispatch path instead
        of failing its neighbours (handlers are idempotent by pipeline
        contract, so the partial re-execution is absorbed)."""
        from copilot_for_consensus_tpu.obs import trace

        cb = self._batch_routes[rk]
        for m in msgs:
            trace.annotate_delivery(m["envelope"],
                                    int(m.get("attempts", 0)))
        self._current = (rk, f"wave x{len(msgs)}", time.monotonic())
        try:
            outcomes = cb([m["envelope"] for m in msgs])
            if outcomes is None:
                outcomes = [None] * len(msgs)
        except Exception:
            for m in msgs:
                self._dispatch(m)
            return
        finally:
            self._current = None
        acks = [m["id"] for m, out in zip(msgs, outcomes) if out is None]
        nacks = [(m, out) for m, out in zip(msgs, outcomes)
                 if out is not None]
        self._settle(acks, nacks)

    def drain(self, max_messages: int | None = None) -> int:
        """Process what's queued now; returns the number handled.
        Fetched waves group into consecutive same-key runs: keys with a
        registered batch route dispatch as one wave, the rest one by
        one."""
        n = 0
        while max_messages is None or n < max_messages:
            if self.faults is not None:
                try:
                    self.faults.check("fetch")
                except Exception as exc:
                    # surfaces exactly like a broker outage so
                    # start_consuming backs off and reconnects
                    raise PublishError(
                        f"injected fetch fault: {exc}") from exc
            want = self.batch if max_messages is None else min(
                self.batch, max_messages - n)
            reply = self._client.request(
                {"op": "fetch", "rks": sorted(self._routes),
                 "group": self.group, "max": want})
            msgs = reply.get("msgs", [])
            if not msgs:
                break
            i = 0
            while i < len(msgs):
                rk = msgs[i]["rk"]
                j = i + 1
                if rk in self._batch_routes:
                    while j < len(msgs) and msgs[j]["rk"] == rk:
                        j += 1
                    self._dispatch_batch(rk, msgs[i:j])
                else:
                    self._dispatch(msgs[i])
                n += j - i
                i = j
        return n

    def start_consuming(self):
        """Consume until stop(); survives broker outages by backing off and
        reconnecting (the reference subscriber's reconnect loop,
        ``rabbitmq_subscriber.py``)."""
        self._stop.clear()
        backoff = self.poll_interval_s
        while not self._stop.is_set():
            try:
                n = self.drain()
            except PublishError:
                self._stop.wait(min(backoff, 5.0))
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = self.poll_interval_s
            if n == 0:
                self._stop.wait(self.poll_interval_s)

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        self._client.close()
        if self._counts_client is not None:
            self._counts_client.close()


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="copilot bus broker")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--db", default=":memory:",
                    help="sqlite path for durable queues")
    ap.add_argument("--max-redeliveries", type=int, default=3)
    ap.add_argument("--lease-s", type=float, default=DEFAULT_LEASE_S)
    args = ap.parse_args(argv)
    broker = Broker(port=args.port, db_path=args.db, host=args.host,
                    max_redeliveries=args.max_redeliveries,
                    lease_s=args.lease_s)
    print(f"broker listening on {broker.address} (db={args.db})",
          flush=True)
    broker.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
