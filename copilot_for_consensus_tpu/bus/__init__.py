"""Message bus abstraction: event pub/sub with pluggable drivers.

Capability parity with the reference's ``copilot_message_bus`` package
(ABCs ``base.py:11,43``; RabbitMQ/AzureServiceBus/Noop drivers; validating
decorators — SURVEY.md §2.1). Drivers here:

* ``inproc`` — a process-local topic broker with durable-queue semantics
  (ack / nack-requeue / redelivery cap / dead-letter), the default for
  single-host runs and tests (the reference's fake-backend strategy, §4);
* ``broker`` (alias ``zmq``) — the inter-process tier: one ZMQ ROUTER
  broker with sqlite-durable queues, publisher confirms, ack/nack-requeue
  leases and dead-lettering (``bus/broker.py``);
* ``noop``  — drops everything.

On TPU pods this host bus is tier 2 of the two-tier comms design
(SURVEY.md §5 "Distributed communication backend"): XLA collectives move
tensors over ICI inside the slice; this bus moves *events* between host
services and the resident TPU engine.
"""

from copilot_for_consensus_tpu.bus.base import (
    EventPublisher,
    EventSubscriber,
    PublishError,
)
from copilot_for_consensus_tpu.bus.broker import (
    Broker,
    BrokerPublisher,
    BrokerSubscriber,
)
from copilot_for_consensus_tpu.bus.inproc import InProcBroker, get_broker

__all__ = [
    "EventPublisher",
    "EventSubscriber",
    "PublishError",
    "Broker",
    "BrokerPublisher",
    "BrokerSubscriber",
    "InProcBroker",
    "get_broker",
]
