"""Deterministic seeded fault-injection plane for the PIPELINE boundaries.

PR 7 gave the serving engines a fault plane (``engine/faults.py``) and
the machinery to survive what it injects. This module extends the same
vocabulary — :class:`FaultSpec` / :class:`FaultPlan` (kind, error|hang,
occurrence-window or seeded rate, JSON round-trip) driven by one
:class:`FaultInjector` — to the host-side pipeline's I/O boundaries, so
the pipeline chaos harness (``tests/test_bus_resilience.py``,
``BENCH_PRESET=pipeline_chaos``) can script broker outages, store
hiccups and poison traffic deterministically.

Boundaries (the :data:`PIPELINE_FAULT_KINDS`):

* ``publish`` / ``fetch`` / ``ack`` — the broker client boundaries.
  Wired directly into :class:`~.broker.BrokerPublisher` /
  :class:`~.broker.BrokerSubscriber` (attribute ``faults``): an
  injected ``publish`` fault is handled exactly like a broker outage
  (the envelope parks in the publish outbox and replays), an injected
  ``fetch`` fault surfaces as :class:`~.base.PublishError` (the
  consume loop backs off and reconnects), an injected ``ack`` fault
  suppresses the ack so the lease expires and the message redelivers —
  the at-least-once path a consumer crash takes.
* ``store_write`` / ``vector_upsert`` / ``archive_read`` — the storage
  boundaries, injected via the wrapper classes below
  (:class:`FaultingDocumentStore`, :class:`FaultingVectorStore`,
  :class:`FaultingArchiveStore`).

Transient vs terminal: storage faults default to **transient**
(:class:`TransientPipelineFault` is a :class:`RetryableError`, so the
service retry policy backs off and the lease/redelivery path applies);
kinds listed in ``terminal_kinds`` raise the non-retryable
:class:`PipelineFaultError` instead — which the subscriber classifies
as poison and quarantines straight to the broker dead-letter table
(``docs/RESILIENCE.md`` poison-vs-transient table).

Everything here is import-light host code (no jax, no zmq).
"""

from __future__ import annotations

from typing import Iterable

from copilot_for_consensus_tpu.core.retry import RetryableError
from copilot_for_consensus_tpu.engine.faults import (  # noqa: F401  (re-export)
    PERSISTENT,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    resolve_faults,
)

#: pipeline boundaries the bus/storage layers wire fault points for
#: (doc + test anchor; plans may name any kind — unknown kinds simply
#: never fire)
PIPELINE_FAULT_KINDS = ("publish", "fetch", "ack", "store_write",
                        "vector_upsert", "archive_read")


class PipelineFaultError(RuntimeError):
    """A scripted TERMINAL pipeline fault: redelivery cannot fix it, so
    the subscriber's classification sends the envelope straight to the
    dead-letter table (poison quarantine)."""

    def __init__(self, message: str, *, kind: str = "",
                 occurrence: int = 0):
        super().__init__(message)
        self.kind = kind
        self.occurrence = occurrence


class TransientPipelineFault(PipelineFaultError, RetryableError):
    """A scripted TRANSIENT pipeline fault: being a
    :class:`RetryableError` it rides the existing recovery spine —
    in-process retry with backoff, then lease/redelivery."""


class FaultBoundary:
    """One plan's runtime state over the pipeline boundaries.

    Thin adapter over :class:`engine.faults.FaultInjector`: ``check``
    counts the occurrence and translates an :class:`InjectedFault`
    into the pipeline's transient/terminal error classes, preserving
    kind and occurrence (hang mode is inherited unchanged — stop-aware
    ``Event.wait``, released by :meth:`release_hangs`)."""

    def __init__(self, faults, terminal_kinds: Iterable[str] = ()):
        self.injector = resolve_faults(faults)
        self.terminal_kinds = set(terminal_kinds)

    def check(self, kind: str) -> None:
        if self.injector is None:
            return
        try:
            self.injector.check(kind)
        except InjectedFault as exc:
            cls = (PipelineFaultError if kind in self.terminal_kinds
                   else TransientPipelineFault)
            raise cls(str(exc), kind=kind,
                      occurrence=exc.occurrence) from None

    def release_hangs(self) -> None:
        if self.injector is not None:
            self.injector.release_hangs()

    def stats(self) -> dict:
        return {} if self.injector is None else self.injector.stats()


def resolve_boundary(faults, terminal_kinds: Iterable[str] = ()
                     ) -> FaultBoundary | None:
    """``faults=`` argument semantics for the bus/storage wrappers:
    None/False disables; a :class:`FaultBoundary` is shared as-is (one
    plan across publisher + subscriber + stores — how the pipeline
    chaos preset faults every boundary together); anything else goes
    through :func:`engine.faults.resolve_faults`."""
    if faults is None or faults is False:
        return None
    if isinstance(faults, FaultBoundary):
        return faults
    return FaultBoundary(faults, terminal_kinds=terminal_kinds)


class _Wrapper:
    """Delegating base: everything not explicitly intercepted passes
    through to the wrapped object."""

    def __init__(self, inner, faults, terminal_kinds: Iterable[str] = ()):
        self.inner = inner
        self.faults = resolve_boundary(faults, terminal_kinds)

    def _check(self, kind: str) -> None:
        if self.faults is not None:
            self.faults.check(kind)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class FaultingDocumentStore(_Wrapper):
    """Document-store wrapper firing ``store_write`` at every mutating
    call. Reads pass through untouched — a read fault would masquerade
    as the event-vs-DB visibility race the retry policy already
    covers, teaching the harness nothing new."""

    def upsert_document(self, collection, doc):
        self._check("store_write")
        return self.inner.upsert_document(collection, doc)

    def insert_document(self, collection, doc):
        self._check("store_write")
        return self.inner.insert_document(collection, doc)

    def insert_or_ignore(self, collection, doc):
        self._check("store_write")
        return self.inner.insert_or_ignore(collection, doc)

    def insert_many(self, collection, docs, ignore_duplicates=False):
        self._check("store_write")
        return self.inner.insert_many(collection, docs,
                                      ignore_duplicates)

    def update_document(self, collection, doc_id, fields):
        self._check("store_write")
        return self.inner.update_document(collection, doc_id, fields)

    def update_documents(self, collection, doc_ids, fields):
        # One boundary check per wave: the batched hot paths pay one
        # store round-trip, so they pay one fault-fire opportunity —
        # a chaos window lands on the whole wave, whose dispatch then
        # isolates per message.
        self._check("store_write")
        return self.inner.update_documents(collection, doc_ids, fields)

    def delete_document(self, collection, doc_id):
        self._check("store_write")
        return self.inner.delete_document(collection, doc_id)

    def delete_documents(self, collection, flt):
        self._check("store_write")
        return self.inner.delete_documents(collection, flt)


class FaultingVectorStore(_Wrapper):
    """Vector-store wrapper firing ``vector_upsert`` on ingest-path
    mutations."""

    def add_embeddings(self, items):
        self._check("vector_upsert")
        return self.inner.add_embeddings(items)

    def delete(self, ids):
        self._check("vector_upsert")
        return self.inner.delete(ids)

    def delete_by_filter(self, flt):
        self._check("vector_upsert")
        return self.inner.delete_by_filter(flt)


class FaultingArchiveStore(_Wrapper):
    """Archive-store wrapper firing ``archive_read`` where parsing
    loads raw bytes (the boundary a blob-store outage hits)."""

    def load(self, archive_id):
        self._check("archive_read")
        return self.inner.load(archive_id)
