"""ZeroMQ bus driver: cross-process/cross-host event fan-out.

The distributed-bus role the reference fills with RabbitMQ (SURVEY.md §5
"Distributed communication backend", tier 2 of the two-tier design). A PUSH/
PULL pipeline per routing key gives competing-consumer semantics (each
message to exactly one consumer), like one durable queue per routing key.

Topology: a publisher binds one PUSH socket per routing key at
``base_port + hash(rk) % port_range`` on ``host``; subscribers connect PULL
sockets. For multi-host, point ``host`` at the publisher's address. This
driver favors simplicity over broker durability — undelivered messages live
in ZMQ buffers, so it's for throughput paths, not the durability-critical
ones (use the sqlite-backed outbox in storage for those).
"""

from __future__ import annotations

import json
import threading
from typing import Any

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PublishError,
)

try:
    import zmq

    HAS_ZMQ = True
except ImportError:  # pragma: no cover - environment without pyzmq
    HAS_ZMQ = False


def _port_for(routing_key: str, base_port: int, port_range: int) -> int:
    # Stable port per routing key (sha-free: must match across processes).
    h = 0
    for ch in routing_key:
        h = (h * 131 + ord(ch)) % port_range
    return base_port + h


class ZmqPublisher(EventPublisher):
    def __init__(self, config: Any = None):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        cfg = dict(config or {})
        self.host = cfg.get("host", "127.0.0.1")
        self.base_port = int(cfg.get("base_port", 5700))
        self.port_range = int(cfg.get("port_range", 64))
        self._ctx = zmq.Context.instance()
        self._sockets: dict[str, Any] = {}
        self._lock = threading.Lock()

    def _socket(self, routing_key: str):
        with self._lock:
            if routing_key not in self._sockets:
                sock = self._ctx.socket(zmq.PUSH)
                sock.setsockopt(zmq.SNDHWM, 100000)
                sock.setsockopt(zmq.LINGER, 1000)
                port = _port_for(routing_key, self.base_port, self.port_range)
                sock.bind(f"tcp://{self.host}:{port}")
                self._sockets[routing_key] = sock
            return self._sockets[routing_key]

    def publish_envelope(self, envelope, routing_key=None):
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        try:
            self._socket(routing_key).send(json.dumps(envelope).encode())
        except zmq.ZMQError as exc:
            raise PublishError(str(exc)) from exc

    def close(self):
        with self._lock:
            for sock in self._sockets.values():
                sock.close()
            self._sockets.clear()


class ZmqSubscriber(EventSubscriber):
    def __init__(self, config: Any = None):
        if not HAS_ZMQ:
            raise PublishError("pyzmq is not available")
        cfg = dict(config or {})
        self.host = cfg.get("host", "127.0.0.1")
        self.base_port = int(cfg.get("base_port", 5700))
        self.port_range = int(cfg.get("port_range", 64))
        self.max_redeliveries = int(cfg.get("max_redeliveries", 3))
        self._ctx = zmq.Context.instance()
        self._poller = zmq.Poller()
        self._handlers: dict[Any, EventCallback] = {}
        self._stop = threading.Event()

    def subscribe(self, routing_keys, callback):
        for rk in routing_keys:
            sock = self._ctx.socket(zmq.PULL)
            sock.setsockopt(zmq.RCVHWM, 100000)
            port = _port_for(rk, self.base_port, self.port_range)
            sock.connect(f"tcp://{self.host}:{port}")
            self._poller.register(sock, zmq.POLLIN)
            self._handlers[sock] = callback

    def _dispatch(self, sock, callback) -> None:
        raw = sock.recv()
        envelope = json.loads(raw)
        attempts = 0
        while True:
            try:
                callback(envelope)
                return
            except Exception:
                attempts += 1
                if attempts >= self.max_redeliveries:
                    return  # dead-letter: drop after cap (no broker to hold it)

    def start_consuming(self):
        self._stop.clear()
        while not self._stop.is_set():
            for sock, _ in self._poller.poll(timeout=100):
                self._dispatch(sock, self._handlers[sock])

    def drain(self, max_messages: int | None = None) -> int:
        n = 0
        while max_messages is None or n < max_messages:
            events = dict(self._poller.poll(timeout=50))
            if not events:
                break
            for sock in events:
                self._dispatch(sock, self._handlers[sock])
                n += 1
        return n

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        for sock in self._handlers:
            self._poller.unregister(sock)
            sock.close()
        self._handlers.clear()
