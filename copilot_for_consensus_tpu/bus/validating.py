"""Validating decorators: schema-check every envelope at the bus boundary.

Parity with the reference's ``validating_publisher.py`` /
``validating_subscriber.py`` cross-cutting wrappers — invalid events are
rejected at publish time (raise) and quarantined at consume time (routed to
the subscriber's invalid-event hook instead of the handler).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PublishError,
)
from copilot_for_consensus_tpu.core.validation import (
    FileSchemaProvider,
    SchemaValidationError,
    validate_envelope,
)


class ValidatingPublisher(EventPublisher):
    def __init__(self, inner: EventPublisher,
                 provider: FileSchemaProvider | None = None):
        self.inner = inner
        self.provider = provider

    def connect(self):
        self.inner.connect()

    def close(self):
        self.inner.close()

    def publish_envelope(self, envelope, routing_key=None):
        try:
            validate_envelope(envelope, self.provider)
        except (SchemaValidationError, FileNotFoundError) as exc:
            raise PublishError(f"refusing to publish invalid event: {exc}") from exc
        self.inner.publish_envelope(envelope, routing_key)


class ValidatingSubscriber(EventSubscriber):
    def __init__(self, inner: EventSubscriber,
                 provider: FileSchemaProvider | None = None,
                 on_invalid: Callable[[Mapping[str, Any], Exception], None] | None = None):
        self.inner = inner
        self.provider = provider
        self.on_invalid = on_invalid
        self.invalid_count = 0

    def connect(self):
        self.inner.connect()

    def close(self):
        self.inner.close()

    def subscribe(self, routing_keys, callback: EventCallback):
        def guarded(envelope):
            try:
                validate_envelope(envelope, self.provider)
            except (SchemaValidationError, FileNotFoundError) as exc:
                self.invalid_count += 1
                if self.on_invalid is not None:
                    self.on_invalid(envelope, exc)
                return  # ack: an invalid event can never become valid by retry
            callback(envelope)

        self.inner.subscribe(routing_keys, guarded)

    def start_consuming(self):
        self.inner.start_consuming()

    def stop(self):
        self.inner.stop()

    def __getattr__(self, name):
        return getattr(self.inner, name)
