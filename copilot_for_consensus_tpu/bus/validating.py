"""Validating decorators: schema-check every envelope at the bus boundary.

Parity with the reference's ``validating_publisher.py`` /
``validating_subscriber.py`` cross-cutting wrappers — invalid events are
rejected at publish time (raise) and quarantined at consume time (routed to
the subscriber's invalid-event hook instead of the handler).
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PoisonEnvelope,
    PublishError,
)
from copilot_for_consensus_tpu.core.validation import (
    FileSchemaProvider,
    SchemaValidationError,
    validate_envelope,
)


class ValidatingPublisher(EventPublisher):
    def __init__(self, inner: EventPublisher,
                 provider: FileSchemaProvider | None = None):
        self.inner = inner
        self.provider = provider

    def connect(self):
        self.inner.connect()

    def publish_envelope(self, envelope, routing_key=None):
        try:
            validate_envelope(envelope, self.provider)
        except (SchemaValidationError, FileNotFoundError) as exc:
            raise PublishError(f"refusing to publish invalid event: {exc}") from exc
        self.inner.publish_envelope(envelope, routing_key)

    def close(self) -> None:
        # Explicit: the base class's concrete no-op close() would
        # otherwise shadow delegation and leak the inner driver's
        # resources (the broker publisher's outbox + replay thread).
        self.inner.close()

    def saturation(self) -> dict[str, int]:
        # Explicit for the same reason as close(): EventPublisher
        # defines a concrete {} default, so __getattr__ alone would
        # never fire and the wrapper would hide the inner driver's
        # depth feedback — silently disabling the services' consumption
        # throttle and the ingestion pacer in the assembled pipeline
        # (every service publisher is validating-wrapped).
        return self.inner.saturation()

    def pending_depths(self) -> dict[str, int]:
        return self.inner.pending_depths()

    def __getattr__(self, name):
        # Driver capability passthrough (outbox_stats()/faults/...) —
        # same delegation contract as ValidatingSubscriber below. Only
        # covers names the base class does NOT define; anything with a
        # concrete default needs explicit delegation above.
        return getattr(self.inner, name)


class ValidatingSubscriber(EventSubscriber):
    def __init__(self, inner: EventSubscriber,
                 provider: FileSchemaProvider | None = None,
                 on_invalid: Callable[[Mapping[str, Any], Exception], None] | None = None):
        self.inner = inner
        self.provider = provider
        self.on_invalid = on_invalid
        self.invalid_count = 0

    def connect(self):
        self.inner.connect()

    def close(self):
        self.inner.close()

    def subscribe(self, routing_keys, callback: EventCallback):
        def guarded(envelope):
            try:
                validate_envelope(envelope, self.provider)
            except (SchemaValidationError, FileNotFoundError) as exc:
                self.invalid_count += 1
                if self.on_invalid is not None:
                    self.on_invalid(envelope, exc)
                # An invalid event can never become valid by retry:
                # poison-quarantine it (drivers with a dead-letter
                # table park it there with the reason, skipping the
                # redelivery budget) instead of silently acking it out
                # of existence.
                raise PoisonEnvelope(
                    f"schema validation failed: {exc}") from exc
            callback(envelope)

        self.inner.subscribe(routing_keys, guarded)

    def subscribe_batch(self, routing_keys, callback) -> bool:
        """Explicit delegation (the base class defines a concrete
        ``return False`` default — ``__getattr__`` alone would never
        fire, silently disabling batch dispatch through the wrapper):
        validates each envelope of the wave, quarantines the invalid
        ones per-envelope (``PoisonEnvelope`` outcome, same contract as
        the single-dispatch ``guarded`` path), and forwards only the
        valid subset to the service's wave callback."""

        def guarded_batch(envelopes):
            outcomes: list = [None] * len(envelopes)
            valid_idx: list[int] = []
            valid: list = []
            for i, envelope in enumerate(envelopes):
                try:
                    validate_envelope(envelope, self.provider)
                except (SchemaValidationError, FileNotFoundError) as exc:
                    self.invalid_count += 1
                    if self.on_invalid is not None:
                        self.on_invalid(envelope, exc)
                    outcomes[i] = PoisonEnvelope(
                        f"schema validation failed: {exc}")
                else:
                    valid_idx.append(i)
                    valid.append(envelope)
            if valid:
                inner_out = callback(valid)
                if inner_out is None:
                    inner_out = [None] * len(valid)
                for i, out in zip(valid_idx, inner_out):
                    outcomes[i] = out
            return outcomes

        return self.inner.subscribe_batch(routing_keys, guarded_batch)

    def start_consuming(self):
        self.inner.start_consuming()

    def stop(self):
        self.inner.stop()

    def __getattr__(self, name):
        return getattr(self.inner, name)
