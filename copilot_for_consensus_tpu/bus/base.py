"""Publisher/Subscriber ABCs shared by every bus driver."""

from __future__ import annotations

import abc
from typing import Any, Callable, Mapping

from copilot_for_consensus_tpu.core.events import Event

# Callback receives the envelope dict; raising triggers nack/requeue.
EventCallback = Callable[[Mapping[str, Any]], None]

# Batch callback (opt-in, `subscribe_batch`): receives a wave of
# same-routing-key envelopes and returns one outcome per envelope IN
# ORDER — None acks; an exception instance classifies exactly like the
# single-dispatch raise (PoisonEnvelope / non-retryable → quarantine,
# RetryableError / PublishError → nack-redeliver). Returning None means
# "all acked". Raising from the callback itself signals a wave-level
# infrastructure failure: drivers fall back to per-envelope dispatch so
# one bad message can never fail its neighbours.
BatchEventCallback = Callable[[list], "list[BaseException | None] | None"]


class PublishError(Exception):
    pass


class BusSaturated(PublishError):
    """Structured backpressure signal: the bus cannot absorb more work.

    Raised by publishers when the durable publish outbox overflows
    (``reason="outbox-full"``) — the caller must slow down or shed;
    also carried (not raised) by ``saturation()`` when a routing key's
    broker-side depth crossed the high watermark
    (``reason="queue-depth"``). Analogue of the engine's
    ``EngineOverloaded``: honest backpressure instead of silent loss.
    """

    def __init__(self, message: str, *, routing_key: str = "",
                 depth: int = 0, limit: int = 0,
                 reason: str = "queue-depth"):
        super().__init__(message)
        self.routing_key = routing_key
        self.depth = depth
        self.limit = limit
        self.reason = reason


class PoisonEnvelope(Exception):
    """Classification signal: this envelope can never be processed, no
    matter how often it redelivers — schema-invalid at the bus edge
    (``bus/validating.py``) or a deterministic (non-``RetryableError``)
    handler failure. Subscriber drivers that support quarantine skip
    the redelivery budget and park it in the dead-letter table with
    ``reason``; drivers without poison support degrade to the normal
    redelivery budget."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class EventPublisher(abc.ABC):
    """Publishes event envelopes to a topic exchange by routing key."""

    def connect(self) -> None:  # drivers override when they hold connections
        pass

    def close(self) -> None:
        pass

    def saturation(self) -> dict[str, int]:
        """Routing keys whose last-known broker-side depth is at/above
        this publisher's high watermark (empty when unconfigured or
        healthy) — the signal services throttle consumption on.
        Drivers without depth feedback return {}."""
        return {}

    def pending_depths(self) -> dict[str, int]:
        """Best-effort snapshot of broker-side pending depth per
        routing key (the ingestion pacing surface). Drivers without an
        introspection channel — or with an unreachable broker —
        return {}."""
        return {}

    @abc.abstractmethod
    def publish_envelope(self, envelope: Mapping[str, Any],
                         routing_key: str | None = None) -> None: ...

    def publish(self, event: Event, routing_key: str | None = None) -> None:
        """Publish a typed event (envelope built + routing key from type)."""
        self.publish_envelope(
            event.to_envelope(), routing_key or type(event).routing_key
        )

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.close()


class EventSubscriber(abc.ABC):
    """Consumes envelopes from queues bound to routing keys.

    Semantics contract (all drivers):
    * one logical queue per routing key; competing subscribers on the same
      queue share work;
    * the callback completing normally acks the message;
    * the callback raising requeues it, up to ``max_redeliveries``, after
      which the envelope goes to the dead-letter queue ``<rk>.dlq``.
    """

    def connect(self) -> None:
        pass

    def close(self) -> None:
        pass

    @abc.abstractmethod
    def subscribe(self, routing_keys: list[str], callback: EventCallback) -> None: ...

    def subscribe_batch(self, routing_keys: list[str],
                        callback: BatchEventCallback) -> bool:
        """Opt-in batch dispatch: register a wave callback for keys the
        subscriber ALSO has a single-envelope route for (the fallback
        path). Returns True when the driver supports batch dispatch;
        this default (drivers without it) is False and registers
        nothing — callers keep the per-envelope path.

        NOTE for wrappers with ``__getattr__`` delegation: this is a
        concrete base-class default, so delegating wrappers must
        forward it explicitly (the race-wrapper-shadow contract)."""
        return False

    @abc.abstractmethod
    def start_consuming(self) -> None:
        """Blocking consume loop (runs until stop())."""

    @abc.abstractmethod
    def stop(self) -> None: ...

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.close()


class NoopPublisher(EventPublisher):
    def publish_envelope(self, envelope, routing_key=None):
        pass


class NoopSubscriber(EventSubscriber):
    def subscribe(self, routing_keys, callback):
        pass

    def start_consuming(self):
        pass

    def stop(self):
        pass
