"""Publisher/Subscriber ABCs shared by every bus driver."""

from __future__ import annotations

import abc
from typing import Any, Callable, Mapping

from copilot_for_consensus_tpu.core.events import Event

# Callback receives the envelope dict; raising triggers nack/requeue.
EventCallback = Callable[[Mapping[str, Any]], None]


class PublishError(Exception):
    pass


class EventPublisher(abc.ABC):
    """Publishes event envelopes to a topic exchange by routing key."""

    def connect(self) -> None:  # drivers override when they hold connections
        pass

    def close(self) -> None:
        pass

    @abc.abstractmethod
    def publish_envelope(self, envelope: Mapping[str, Any],
                         routing_key: str | None = None) -> None: ...

    def publish(self, event: Event, routing_key: str | None = None) -> None:
        """Publish a typed event (envelope built + routing key from type)."""
        self.publish_envelope(
            event.to_envelope(), routing_key or type(event).routing_key
        )

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.close()


class EventSubscriber(abc.ABC):
    """Consumes envelopes from queues bound to routing keys.

    Semantics contract (all drivers):
    * one logical queue per routing key; competing subscribers on the same
      queue share work;
    * the callback completing normally acks the message;
    * the callback raising requeues it, up to ``max_redeliveries``, after
      which the envelope goes to the dead-letter queue ``<rk>.dlq``.
    """

    def connect(self) -> None:
        pass

    def close(self) -> None:
        pass

    @abc.abstractmethod
    def subscribe(self, routing_keys: list[str], callback: EventCallback) -> None: ...

    @abc.abstractmethod
    def start_consuming(self) -> None:
        """Blocking consume loop (runs until stop())."""

    @abc.abstractmethod
    def stop(self) -> None: ...

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.close()


class NoopPublisher(EventPublisher):
    def publish_envelope(self, envelope, routing_key=None):
        pass


class NoopSubscriber(EventSubscriber):
    def subscribe(self, routing_keys, callback):
        pass

    def start_consuming(self):
        pass

    def stop(self):
        pass
