"""In-process topic broker: the default bus for single-host pipelines and
tests.

Implements the same observable semantics as the reference's RabbitMQ setup
(topic exchange ``copilot.events``, one durable queue per routing key,
manual ack / nack-requeue, redelivery cap with dead-lettering —
``rabbitmq_subscriber.py:504-560``) without a broker process. Publishers and
subscribers rendezvous on a named broker in a process-global registry.

Delivery modes:
* ``drain()`` — pump queues until empty on the caller's thread (tests and
  the single-process pipeline runner);
* ``start_consuming()`` — blocking loop with a condition variable (service
  deployments, one consumer thread per service).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
)

DEFAULT_EXCHANGE = "copilot.events"
DLQ_SUFFIX = ".dlq"


@dataclass
class _Queue:
    name: str
    items: deque = field(default_factory=deque)  # (envelope, redeliveries)
    callbacks: list[EventCallback] = field(default_factory=list)
    rr_next: int = 0  # round-robin cursor over competing consumers


class InProcBroker:
    def __init__(self, name: str = DEFAULT_EXCHANGE, max_redeliveries: int = 3):
        self.name = name
        self.max_redeliveries = max_redeliveries
        self._queues: dict[str, _Queue] = {}
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self.published_count = 0
        self.dead_lettered: list[tuple[str, Mapping[str, Any]]] = []

    def queue(self, routing_key: str) -> _Queue:
        with self._lock:
            if routing_key not in self._queues:
                self._queues[routing_key] = _Queue(routing_key)
            return self._queues[routing_key]

    def publish(self, envelope: Mapping[str, Any], routing_key: str) -> None:
        with self._work:
            self.queue(routing_key).items.append((dict(envelope), 0))
            self.published_count += 1
            self._work.notify_all()

    def bind(self, routing_key: str, callback: EventCallback) -> None:
        with self._lock:
            self.queue(routing_key).callbacks.append(callback)

    def unbind(self, routing_key: str, callback: EventCallback) -> None:
        with self._lock:
            q = self.queue(routing_key)
            if callback in q.callbacks:
                q.callbacks.remove(callback)

    def queue_depth(self, routing_key: str) -> int:
        with self._lock:
            return len(self.queue(routing_key).items)

    def _pop_ready(self) -> tuple[_Queue, Mapping[str, Any], int, EventCallback] | None:
        with self._lock:
            for q in self._queues.values():
                if q.items and q.callbacks:
                    envelope, redeliveries = q.items.popleft()
                    cb = q.callbacks[q.rr_next % len(q.callbacks)]
                    q.rr_next += 1
                    return q, envelope, redeliveries, cb
        return None

    def _dispatch_one(self) -> bool:
        """Deliver one message; returns False when nothing is deliverable."""
        ready = self._pop_ready()
        if ready is None:
            return False
        q, envelope, redeliveries, cb = ready
        try:
            cb(envelope)  # normal return = ack
        except Exception:
            if redeliveries + 1 >= self.max_redeliveries:
                with self._work:
                    self.dead_lettered.append((q.name, envelope))
                    self.queue(q.name + DLQ_SUFFIX).items.append((envelope, 0))
                    self._work.notify_all()
            else:
                with self._work:
                    q.items.append((envelope, redeliveries + 1))
                    self._work.notify_all()
        return True

    def drain(self, max_messages: int | None = None) -> int:
        """Dispatch until all bound queues are empty. Returns message count.

        Messages whose handlers publish more messages are processed too —
        this runs the whole event cascade to quiescence.
        """
        n = 0
        while max_messages is None or n < max_messages:
            if not self._dispatch_one():
                break
            n += 1
        return n

    def run_forever(self, stop_flag: threading.Event) -> None:
        while not stop_flag.is_set():
            if not self._dispatch_one():
                with self._work:
                    self._work.wait(timeout=0.1)


_BROKERS: dict[str, InProcBroker] = {}
_BROKERS_LOCK = threading.Lock()


def get_broker(name: str = DEFAULT_EXCHANGE) -> InProcBroker:
    with _BROKERS_LOCK:
        if name not in _BROKERS:
            _BROKERS[name] = InProcBroker(name)
        return _BROKERS[name]


def reset_broker(name: str = DEFAULT_EXCHANGE) -> None:
    with _BROKERS_LOCK:
        _BROKERS.pop(name, None)


class InProcPublisher(EventPublisher):
    def __init__(self, config: Any = None, broker: InProcBroker | None = None):
        cfg = dict(config or {})
        self.broker = broker or get_broker(cfg.get("exchange", DEFAULT_EXCHANGE))

    def publish_envelope(self, envelope, routing_key=None):
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        self.broker.publish(envelope, routing_key)


class InProcSubscriber(EventSubscriber):
    def __init__(self, config: Any = None, broker: InProcBroker | None = None):
        cfg = dict(config or {})
        self.broker = broker or get_broker(cfg.get("exchange", DEFAULT_EXCHANGE))
        self._bound: list[tuple[str, EventCallback]] = []
        self._stop = threading.Event()

    def subscribe(self, routing_keys, callback):
        for rk in routing_keys:
            self.broker.bind(rk, callback)
            self._bound.append((rk, callback))

    def start_consuming(self):
        self._stop.clear()
        self.broker.run_forever(self._stop)

    def drain(self, max_messages: int | None = None) -> int:
        return self.broker.drain(max_messages)

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        for rk, cb in self._bound:
            self.broker.unbind(rk, cb)
        self._bound.clear()
