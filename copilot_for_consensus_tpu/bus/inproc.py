"""In-process topic broker: the default bus for single-host pipelines and
tests.

Implements the same observable semantics as the reference's RabbitMQ setup
(topic exchange ``copilot.events``, one durable queue per routing key,
manual ack / nack-requeue, redelivery cap with dead-lettering —
``rabbitmq_subscriber.py:504-560``) without a broker process. Publishers and
subscribers rendezvous on a named broker in a process-global registry.

Delivery modes:
* ``drain()`` — pump queues until empty on the caller's thread (tests and
  the single-process pipeline runner);
* ``start_consuming()`` — blocking loop with a condition variable (service
  deployments, one consumer thread per service).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping

from copilot_for_consensus_tpu.bus.base import (
    EventCallback,
    EventPublisher,
    EventSubscriber,
    PoisonEnvelope,
)

DEFAULT_EXCHANGE = "copilot.events"
DLQ_SUFFIX = ".dlq"


@dataclass
class _Queue:
    """One queue per (routing_key, group): groups model RabbitMQ's
    queue-per-service topology — different groups each get a copy of every
    message (fan-out, e.g. SourceDeletionRequested cleaned up by every
    stage), while consumers inside one group compete round-robin (N
    replicas of one service sharing its queue)."""

    routing_key: str
    group: str
    items: deque = field(default_factory=deque)  # (envelope, redeliveries)
    callbacks: list[EventCallback] = field(default_factory=list)
    rr_next: int = 0  # round-robin cursor over competing consumers

    @property
    def name(self) -> str:
        return self.routing_key


class InProcBroker:
    def __init__(self, name: str = DEFAULT_EXCHANGE, max_redeliveries: int = 3):
        self.name = name
        self.max_redeliveries = max_redeliveries
        self._queues: dict[tuple[str, str], _Queue] = {}
        self._pending: dict[str, deque] = {}   # published before any bind
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self.published_count = 0
        self.dead_lettered: list[tuple[str, Mapping[str, Any]]] = []

    def queue(self, routing_key: str, group: str = "default") -> _Queue:
        with self._lock:
            key = (routing_key, group)
            if key not in self._queues:
                q = _Queue(routing_key, group)
                # First queue on this key inherits messages parked before
                # any consumer was bound (topic exchanges drop these;
                # in-proc keeps them so publish-then-subscribe works).
                parked = self._pending.pop(routing_key, None)
                if parked:
                    q.items.extend(parked)
                self._queues[key] = q
            return self._queues[key]

    def _group_queues(self, routing_key: str) -> list[_Queue]:
        return [q for (rk, _), q in self._queues.items()
                if rk == routing_key]

    def publish(self, envelope: Mapping[str, Any], routing_key: str) -> None:
        with self._work:
            # Only live queues (with consumers) receive copies; otherwise
            # park, so messages never strand in a dead group's queue.
            queues = [q for q in self._group_queues(routing_key)
                      if q.callbacks]
            if queues:
                for q in queues:
                    q.items.append((dict(envelope), 0))
            else:
                self._pending.setdefault(routing_key,
                                         deque()).append((dict(envelope), 0))
            self.published_count += 1
            self._work.notify_all()

    def bind(self, routing_key: str, callback: EventCallback,
             group: str = "default") -> None:
        with self._lock:
            self.queue(routing_key, group).callbacks.append(callback)

    def unbind(self, routing_key: str, callback: EventCallback,
               group: str = "default") -> None:
        with self._lock:
            q = self._queues.get((routing_key, group))
            if q is None:
                return
            if callback in q.callbacks:
                q.callbacks.remove(callback)
            if not q.callbacks:
                # Last consumer gone: drop the queue and re-park its
                # undelivered messages for the next subscriber.
                del self._queues[(routing_key, group)]
                if q.items:
                    self._pending.setdefault(routing_key,
                                             deque()).extend(q.items)

    def queue_depth(self, routing_key: str) -> int:
        with self._lock:
            total = len(self._pending.get(routing_key, ()))
            return total + sum(len(q.items)
                               for q in self._group_queues(routing_key))

    def routing_key_depths(self) -> dict[str, int]:
        """Snapshot of every known routing key's depth (bound queues plus
        parked pre-bind messages) — the metrics/ops introspection surface,
        so callers never reach into broker internals."""
        with self._lock:
            keys = {rk for rk, _ in self._queues} | set(self._pending)
            return {rk: self.queue_depth(rk) for rk in sorted(keys)}

    def consumer_depths(self) -> dict[str, int]:
        """Work a LIVE consumer group is behind on: worst bound-queue
        depth per routing key, parked pre-bind retention EXCLUDED —
        parity with the durable broker's backpressure depth
        (``_QueueStore._depth_locked``). Counting parked rows here
        would make watermark pacing stall forever against keys nothing
        consumes by design (``report.published``, ``*.failed``)."""
        with self._lock:
            out: dict[str, int] = {}
            for (rk, _g), q in self._queues.items():
                out[rk] = max(out.get(rk, 0), len(q.items))
            return out

    def _pop_ready(self) -> tuple[_Queue, Mapping[str, Any], int, EventCallback] | None:
        with self._lock:
            for q in self._queues.values():
                if q.items and q.callbacks:
                    envelope, redeliveries = q.items.popleft()
                    cb = q.callbacks[q.rr_next % len(q.callbacks)]
                    q.rr_next += 1
                    return q, envelope, redeliveries, cb
        return None

    def _dispatch_one(self) -> bool:
        """Deliver one message; returns False when nothing is deliverable."""
        ready = self._pop_ready()
        if ready is None:
            return False
        q, envelope, redeliveries, cb = ready
        if redeliveries:
            from copilot_for_consensus_tpu.obs import trace

            # requeued delivery: annotate the attempt so the stage
            # span records the retry (same parent, never an orphan)
            trace.annotate_delivery(envelope, redeliveries)
        try:
            cb(envelope)  # normal return = ack
        except PoisonEnvelope:
            # Deterministic failure (schema-invalid / non-retryable
            # handler error): redelivery cannot fix it — skip the
            # budget and dead-letter immediately (poison quarantine,
            # same contract as the durable broker's poison nack).
            # publish() takes the broker lock itself and the
            # dead-letter list append is GIL-atomic, so neither runs
            # inside the critical section.
            self.dead_lettered.append((q.name, envelope))
            self.publish(envelope, q.name + DLQ_SUFFIX)
        except Exception:
            if redeliveries + 1 >= self.max_redeliveries:
                self.dead_lettered.append((q.name, envelope))
                self.publish(envelope, q.name + DLQ_SUFFIX)
            else:
                with self._work:
                    q.items.append((envelope, redeliveries + 1))
                    self._work.notify_all()
        return True

    def drain(self, max_messages: int | None = None) -> int:
        """Dispatch until all bound queues are empty. Returns message count.

        Messages whose handlers publish more messages are processed too —
        this runs the whole event cascade to quiescence.
        """
        n = 0
        while max_messages is None or n < max_messages:
            if not self._dispatch_one():
                break
            n += 1
        return n

    def run_forever(self, stop_flag: threading.Event) -> None:
        while not stop_flag.is_set():
            if not self._dispatch_one():
                with self._work:
                    self._work.wait(timeout=0.1)


_BROKERS: dict[str, InProcBroker] = {}
_BROKERS_LOCK = threading.Lock()


def get_broker(name: str = DEFAULT_EXCHANGE) -> InProcBroker:
    with _BROKERS_LOCK:
        if name not in _BROKERS:
            _BROKERS[name] = InProcBroker(name)
        return _BROKERS[name]


def reset_broker(name: str = DEFAULT_EXCHANGE) -> None:
    with _BROKERS_LOCK:
        _BROKERS.pop(name, None)


class InProcPublisher(EventPublisher):
    def __init__(self, config: Any = None, broker: InProcBroker | None = None):
        cfg = dict(config or {})
        self.broker = broker or get_broker(cfg.get("exchange", DEFAULT_EXCHANGE))
        # Depth-watermark saturation surface (driver parity with
        # BrokerPublisher): in-proc consumption shares the publisher's
        # thread, so there is no pacing WAIT here — just the signal the
        # services' throttle hook and the ingestion pacer read.
        self.high_watermark = int(cfg.get("high_watermark", 0) or 0)

    def publish_envelope(self, envelope, routing_key=None):
        if routing_key is None:
            from copilot_for_consensus_tpu.core.events import EVENT_TYPES

            cls = EVENT_TYPES.get(envelope.get("event_type", ""))
            routing_key = cls.routing_key if cls else "unrouted"
        from copilot_for_consensus_tpu.obs import trace

        # trace-context stamp (first publish only — requeues keep it)
        self.broker.publish(trace.inject(envelope, routing_key),
                            routing_key)

    def saturation(self) -> dict[str, int]:
        if not self.high_watermark:
            return {}
        return {rk: d for rk, d in self.broker.consumer_depths().items()
                if d >= self.high_watermark}

    def pending_depths(self) -> dict[str, int]:
        # consumer_depths, not routing_key_depths: the pacing surface
        # must not count parked pre-bind retention (unconsumed terminal
        # keys would read saturated forever and stall ingestion).
        return self.broker.consumer_depths()


class InProcSubscriber(EventSubscriber):
    """``group`` (config key or kwarg) names this consumer's queue group:
    subscribers sharing a group compete for messages (service replicas);
    distinct groups each receive every message (distinct services)."""

    def __init__(self, config: Any = None, broker: InProcBroker | None = None,
                 group: str | None = None):
        cfg = dict(config or {})
        self.broker = broker or get_broker(cfg.get("exchange", DEFAULT_EXCHANGE))
        self.group = group or cfg.get("group") or f"sub-{id(self):x}"
        self._bound: list[tuple[str, EventCallback]] = []
        self._stop = threading.Event()

    def subscribe(self, routing_keys, callback):
        for rk in routing_keys:
            self.broker.bind(rk, callback, group=self.group)
            self._bound.append((rk, callback))

    def start_consuming(self):
        self._stop.clear()
        self.broker.run_forever(self._stop)

    def drain(self, max_messages: int | None = None) -> int:
        return self.broker.drain(max_messages)

    def stop(self):
        self._stop.set()

    def close(self):
        self.stop()
        for rk, cb in self._bound:
            self.broker.unbind(rk, cb, group=self.group)
        self._bound.clear()
