"""Auth service: OIDC login (PKCE), local JWT mint, role store, middleware.

Reference surface: ``auth/app/service.py:171`` (initiate_login ``:398``
with PKCE pair + state + nonce, handle_callback ``:471``, validate_token
``:583``, get_jwks ``:625``), ``app/role_store.py`` (roles admin / reader
/ processor / orchestrator, ``README.md:99-112``), and the JWKS-backed
route middleware (``copilot_auth/middleware.py:52,588``). Network OIDC
providers (github/google/microsoft/datatracker) are config-selectable
and egress-gated; the mock provider carries tests and local runs, as in
the reference (``copilot_auth/mock_provider.py``).
"""

from __future__ import annotations

import abc
import base64
import hashlib
import json
import secrets as pysecrets
import threading
import time
import urllib.parse
from typing import Any

from copilot_for_consensus_tpu.security.jwt import JWTError, JWTManager
from copilot_for_consensus_tpu.services.http import HTTPError, Request

ROLES = ("admin", "reader", "processor", "orchestrator")


class AuthError(Exception):
    pass


# ---------------------------------------------------------------------------
# OIDC providers
# ---------------------------------------------------------------------------


class OIDCProvider(abc.ABC):
    name = "base"
    authorize_url = ""
    token_url = ""
    userinfo_url = ""

    def __init__(self, client_id: str = "", client_secret: str = "",
                 redirect_uri: str = ""):
        self.client_id = client_id
        self.client_secret = client_secret
        self.redirect_uri = redirect_uri

    def build_authorize_url(self, state: str, nonce: str,
                            code_challenge: str) -> str:
        params = {
            "client_id": self.client_id,
            "redirect_uri": self.redirect_uri,
            "response_type": "code",
            "scope": "openid email profile",
            "state": state,
            "nonce": nonce,
            "code_challenge": code_challenge,
            "code_challenge_method": "S256",
        }
        return self.authorize_url + "?" + urllib.parse.urlencode(params)

    def exchange_code(self, code: str, code_verifier: str
                      ) -> dict[str, Any]:
        """code → token response (network)."""
        import urllib.request
        data = urllib.parse.urlencode({
            "grant_type": "authorization_code",
            "code": code,
            "client_id": self.client_id,
            "client_secret": self.client_secret,
            "redirect_uri": self.redirect_uri,
            "code_verifier": code_verifier,
        }).encode()
        req = urllib.request.Request(
            self.token_url, data=data,
            headers={"Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            return json.loads(resp.read())

    def fetch_userinfo(self, access_token: str) -> dict[str, Any]:
        import urllib.request
        req = urllib.request.Request(
            self.userinfo_url,
            headers={"Authorization": f"Bearer {access_token}",
                     "Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            return json.loads(resp.read())


class GitHubProvider(OIDCProvider):
    name = "github"
    authorize_url = "https://github.com/login/oauth/authorize"
    token_url = "https://github.com/login/oauth/access_token"
    userinfo_url = "https://api.github.com/user"


class GoogleProvider(OIDCProvider):
    name = "google"
    authorize_url = "https://accounts.google.com/o/oauth2/v2/auth"
    token_url = "https://oauth2.googleapis.com/token"
    userinfo_url = "https://openidconnect.googleapis.com/v1/userinfo"


class MicrosoftProvider(OIDCProvider):
    name = "microsoft"
    authorize_url = ("https://login.microsoftonline.com/common/oauth2/"
                     "v2.0/authorize")
    token_url = ("https://login.microsoftonline.com/common/oauth2/"
                 "v2.0/token")
    userinfo_url = "https://graph.microsoft.com/oidc/userinfo"


class DatatrackerProvider(OIDCProvider):
    name = "datatracker"
    authorize_url = "https://datatracker.ietf.org/oauth/authorize/"
    token_url = "https://datatracker.ietf.org/oauth/token/"
    userinfo_url = "https://datatracker.ietf.org/oauth/userinfo/"


class MockProvider(OIDCProvider):
    """In-process provider: any code of the form ``mock:<email>``
    exchanges successfully. Test backbone."""

    name = "mock"
    authorize_url = "mock://authorize"

    def exchange_code(self, code: str, code_verifier: str):
        if not code.startswith("mock:"):
            raise AuthError("mock code must be 'mock:<email>'")
        return {"access_token": code}

    def fetch_userinfo(self, access_token: str):
        email = access_token.split(":", 1)[1]
        return {"email": email, "sub": email,
                "name": email.split("@")[0]}


PROVIDERS = {cls.name: cls for cls in
             (GitHubProvider, GoogleProvider, MicrosoftProvider,
              DatatrackerProvider, MockProvider)}


def create_oidc_provider(config: Any = None, **kwargs: Any) -> OIDCProvider:
    cfg = dict(config or {})
    driver = cfg.get("driver", "mock")
    cls = PROVIDERS.get(driver)
    if cls is None:
        raise ValueError(f"unknown oidc provider {driver!r}")
    return cls(client_id=cfg.get("client_id", ""),
               client_secret=cfg.get("client_secret", ""),
               redirect_uri=cfg.get("redirect_uri", ""))


# ---------------------------------------------------------------------------
# Role store (reference auth/app/role_store.py)
# ---------------------------------------------------------------------------


class RoleStore:
    COLLECTION = "user_roles"

    def __init__(self, document_store, default_role: str = "reader"):
        self.store = document_store
        self.default_role = default_role

    def roles_for(self, email: str) -> list[str]:
        doc = self.store.get_document(self.COLLECTION, email)
        if doc is None:
            return [self.default_role] if self.default_role else []
        return list(doc.get("roles", []))

    def assign(self, email: str, roles: list[str]) -> None:
        bad = set(roles) - set(ROLES)
        if bad:
            raise AuthError(f"unknown roles: {sorted(bad)}")
        self.store.upsert_document(self.COLLECTION,
                                   {"_id": email, "email": email,
                                    "roles": sorted(set(roles))})

    def remove(self, email: str) -> bool:
        return self.store.delete_document(self.COLLECTION, email)

    def list_users(self) -> list[dict]:
        return self.store.query_documents(self.COLLECTION, {})


# ---------------------------------------------------------------------------
# Auth service
# ---------------------------------------------------------------------------


class AuthService:
    #: Hard cap on concurrently-pending login states; beyond this the
    #: oldest-expiring entries are evicted (unauthenticated /auth/login
    #: floods must not grow memory without bound).
    MAX_PENDING = 10_000

    def __init__(self, jwt_manager: JWTManager, role_store: RoleStore,
                 providers: dict[str, OIDCProvider] | None = None,
                 login_ttl_seconds: int = 600):
        self.jwt = jwt_manager
        self.roles = role_store
        # No silent mock default: the mock provider exchanges any
        # `mock:<email>` code for a valid identity, so it must be passed
        # in explicitly (the bootstrap layer gates it behind
        # auth.allow_insecure_mock when enforcement is on).
        self.providers = dict(providers or {})
        self.login_ttl_seconds = login_ttl_seconds
        self._pending: dict[str, dict[str, Any]] = {}  # state → login ctx
        # HTTPServer is threaded; prune iterates while callbacks pop.
        self._pending_lock = threading.Lock()

    def _prune_pending_locked(self) -> None:
        now = time.time()
        for state in [s for s, c in self._pending.items()
                      if c["expires"] < now]:
            del self._pending[state]
        while len(self._pending) >= self.MAX_PENDING:
            oldest = min(self._pending, key=lambda s:
                         self._pending[s]["expires"])
            del self._pending[oldest]

    def initiate_login(self, provider: str = "mock") -> dict[str, str]:
        prov = self.providers.get(provider)
        if prov is None:
            raise AuthError(f"unknown provider {provider!r}")
        state = pysecrets.token_urlsafe(24)
        nonce = pysecrets.token_urlsafe(16)
        verifier = pysecrets.token_urlsafe(48)
        challenge = base64.urlsafe_b64encode(
            hashlib.sha256(verifier.encode()).digest()
        ).rstrip(b"=").decode()
        with self._pending_lock:
            self._prune_pending_locked()
            self._pending[state] = {
                "provider": provider, "verifier": verifier, "nonce": nonce,
                "expires": time.time() + self.login_ttl_seconds,
            }
        return {"state": state,
                "authorize_url": prov.build_authorize_url(
                    state, nonce, challenge)}

    def handle_callback(self, state: str, code: str) -> dict[str, Any]:
        with self._pending_lock:
            ctx = self._pending.pop(state, None)
        if ctx is None or ctx["expires"] < time.time():
            raise AuthError("unknown or expired login state")
        prov = self.providers[ctx["provider"]]
        tokens = prov.exchange_code(code, ctx["verifier"])
        info = prov.fetch_userinfo(tokens.get("access_token", ""))
        email = info.get("email") or info.get("sub") or ""
        if not email:
            raise AuthError("provider returned no identity")
        roles = self.roles.roles_for(email)
        token = self.jwt.mint(email, roles=roles,
                              extra_claims={"provider": prov.name,
                                            "name": info.get("name", "")})
        return {"access_token": token, "token_type": "Bearer",
                "email": email, "roles": roles}

    def validate_token(self, token: str) -> dict[str, Any]:
        try:
            return self.jwt.verify(token)
        except JWTError as exc:
            raise AuthError(str(exc)) from exc

    def get_jwks(self) -> dict[str, Any]:
        return self.jwt.jwks()


# ---------------------------------------------------------------------------
# HTTP middleware (reference copilot_auth/middleware.py:52,588)
# ---------------------------------------------------------------------------

PUBLIC_PATHS = ("/health", "/readyz", "/metrics", "/auth/login",
                "/auth/callback", "/.well-known/jwks.json",
                "/.well-known/openid-configuration",
                # The SPA shell and its assets are public; every API call
                # the SPA makes still carries the bearer token.
                "/", "/ui", "/api/openapi.json")


def is_public_path(path: str, public_paths=PUBLIC_PATHS) -> bool:
    """Exact or path-segment-boundary match only: /metrics is public, a
    hypothetical /metrics-private must not be. The ONE definition shared
    by the enforcing middleware and the OpenAPI generator, so the spec
    cannot drift from behavior."""
    return any(path == p or path.startswith(p + "/") for p in public_paths)


def create_jwt_middleware(jwt_manager: JWTManager,
                          required_roles: dict[str, list[str]]
                          | None = None,
                          public_paths=PUBLIC_PATHS):
    """Router middleware: verifies Bearer tokens, stamps claims into
    ``req.context``, enforces per-path-prefix role requirements."""
    required_roles = required_roles or {}

    def middleware(req: Request) -> None:
        if is_public_path(req.path, public_paths):
            return
        header = req.headers.get("Authorization") or req.headers.get(
            "authorization") or ""
        if not header.startswith("Bearer "):
            raise HTTPError(401, "missing bearer token")
        try:
            claims = jwt_manager.verify(header[7:])
        except JWTError as exc:
            raise HTTPError(401, f"invalid token: {exc}")
        req.context.update(claims)
        roles = set(claims.get("roles", []))
        for prefix, needed in required_roles.items():
            if req.path.startswith(prefix):
                if not roles.intersection(needed):
                    raise HTTPError(
                        403, f"requires one of roles {needed}")
                break

    return middleware


def auth_router(service: AuthService, external_base_url: str | None = None):
    """Auth HTTP surface (reference ``auth/main.py:115-1074``).

    ``external_base_url`` is the deployment's public https base; when set
    the discovery document advertises it instead of trusting the
    client-controlled Host / X-Forwarded-Proto headers (which, behind a
    cache or misconfigured proxy, allow discovery-document poisoning)."""
    from copilot_for_consensus_tpu.services.http import Router

    router = Router()

    @router.get("/auth/login")
    def login(req):
        try:
            return service.initiate_login(req.query.get("provider", "mock"))
        except AuthError as exc:
            raise HTTPError(400, str(exc))

    @router.get("/auth/callback")
    def callback(req):
        state = req.query.get("state", "")
        code = req.query.get("code", "")
        try:
            return service.handle_callback(state, code)
        except AuthError as exc:
            raise HTTPError(401, str(exc))

    @router.get("/auth/userinfo")
    def userinfo(req):
        header = req.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            raise HTTPError(401, "missing bearer token")
        try:
            claims = service.validate_token(header[7:])
        except AuthError as exc:
            raise HTTPError(401, str(exc))
        return {"sub": claims.get("sub"), "roles": claims.get("roles"),
                "provider": claims.get("provider")}

    @router.get("/.well-known/jwks.json")
    def jwks(req):
        return service.get_jwks()

    @router.get("/.well-known/openid-configuration")
    def openid_configuration(req):
        """OIDC discovery document for edge-gateway JWT validation.

        Gateways like Azure APIM's validate-jwt resolve signing keys via
        discovery rather than a raw JWKS URL; strict consumers also
        require the authorization/token endpoints and standard response
        types, so the full REQUIRED metadata set is advertised."""
        if external_base_url:
            base = external_base_url.rstrip("/")
        else:
            # Unconfigured (dev) deployments fall back to the request
            # headers; production should set auth.external_base_url.
            host = (req.headers.get("host") or req.headers.get("Host")
                    or "localhost")
            # Behind the TLS edge the advertised URLs must be https — the
            # generated nginx config forwards the original scheme.
            proto = (req.headers.get("x-forwarded-proto")
                     or req.headers.get("X-Forwarded-Proto") or "http")
            base = f"{proto}://{host}"
        return {
            "issuer": service.jwt.issuer,
            "authorization_endpoint": f"{base}/auth/login",
            "token_endpoint": f"{base}/auth/callback",
            "jwks_uri": f"{base}/.well-known/jwks.json",
            "id_token_signing_alg_values_supported": ["RS256"],
            "response_types_supported": ["code", "id_token"],
            "subject_types_supported": ["public"],
        }

    @router.get("/auth/admin/users")
    def list_users(req):
        _require_admin(req, service)
        return {"users": service.roles.list_users()}

    @router.put("/auth/admin/users/{email}")
    def assign_roles(req):
        _require_admin(req, service)
        body = req.json()
        if not isinstance(body, dict) or "roles" not in body:
            raise HTTPError(400, "body must have roles")
        try:
            service.roles.assign(req.params["email"], body["roles"])
        except AuthError as exc:
            raise HTTPError(400, str(exc))
        return {"email": req.params["email"],
                "roles": service.roles.roles_for(req.params["email"])}

    @router.delete("/auth/admin/users/{email}")
    def remove_user(req):
        _require_admin(req, service)
        if not service.roles.remove(req.params["email"]):
            raise HTTPError(404, "user not found")
        return {"status": "removed"}

    return router


def _require_admin(req: Request, service: AuthService) -> None:
    header = req.headers.get("Authorization", "")
    if not header.startswith("Bearer "):
        raise HTTPError(401, "missing bearer token")
    try:
        claims = service.validate_token(header[7:])
    except AuthError as exc:
        raise HTTPError(401, str(exc))
    if "admin" not in claims.get("roles", []):
        raise HTTPError(403, "admin role required")
