"""Auth service: OIDC login (PKCE), local JWT mint, role store, middleware.

Reference surface: ``auth/app/service.py:171`` (initiate_login ``:398``
with PKCE pair + state + nonce, handle_callback ``:471``, validate_token
``:583``, get_jwks ``:625``), ``app/role_store.py`` (roles admin / reader
/ processor / orchestrator, ``README.md:99-112``), and the JWKS-backed
route middleware (``copilot_auth/middleware.py:52,588``). Network OIDC
providers (github/google/microsoft/datatracker) are config-selectable
and egress-gated; the mock provider carries tests and local runs, as in
the reference (``copilot_auth/mock_provider.py``).
"""

from __future__ import annotations

import abc
import base64
import hashlib
import json
import secrets as pysecrets
import threading
import time
import urllib.parse
from typing import Any

from copilot_for_consensus_tpu.security.jwt import JWTError, JWTManager
from copilot_for_consensus_tpu.services.http import HTTPError, Request

ROLES = ("admin", "reader", "processor", "orchestrator")


class AuthError(Exception):
    pass


# ---------------------------------------------------------------------------
# OIDC providers
# ---------------------------------------------------------------------------


class OIDCProvider(abc.ABC):
    name = "base"
    authorize_url = ""
    token_url = ""
    userinfo_url = ""

    def __init__(self, client_id: str = "", client_secret: str = "",
                 redirect_uri: str = ""):
        self.client_id = client_id
        self.client_secret = client_secret
        self.redirect_uri = redirect_uri

    def build_authorize_url(self, state: str, nonce: str,
                            code_challenge: str) -> str:
        params = {
            "client_id": self.client_id,
            "redirect_uri": self.redirect_uri,
            "response_type": "code",
            "scope": "openid email profile",
            "state": state,
            "nonce": nonce,
            "code_challenge": code_challenge,
            "code_challenge_method": "S256",
        }
        return self.authorize_url + "?" + urllib.parse.urlencode(params)

    def exchange_code(self, code: str, code_verifier: str
                      ) -> dict[str, Any]:
        """code → token response (network)."""
        import urllib.request
        data = urllib.parse.urlencode({
            "grant_type": "authorization_code",
            "code": code,
            "client_id": self.client_id,
            "client_secret": self.client_secret,
            "redirect_uri": self.redirect_uri,
            "code_verifier": code_verifier,
        }).encode()
        req = urllib.request.Request(
            self.token_url, data=data,
            headers={"Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            return json.loads(resp.read())

    def fetch_userinfo(self, access_token: str) -> dict[str, Any]:
        import urllib.request
        req = urllib.request.Request(
            self.userinfo_url,
            headers={"Authorization": f"Bearer {access_token}",
                     "Accept": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as resp:
            return json.loads(resp.read())


class GitHubProvider(OIDCProvider):
    name = "github"
    authorize_url = "https://github.com/login/oauth/authorize"
    token_url = "https://github.com/login/oauth/access_token"
    userinfo_url = "https://api.github.com/user"


class GoogleProvider(OIDCProvider):
    name = "google"
    authorize_url = "https://accounts.google.com/o/oauth2/v2/auth"
    token_url = "https://oauth2.googleapis.com/token"
    userinfo_url = "https://openidconnect.googleapis.com/v1/userinfo"


class MicrosoftProvider(OIDCProvider):
    name = "microsoft"
    authorize_url = ("https://login.microsoftonline.com/common/oauth2/"
                     "v2.0/authorize")
    token_url = ("https://login.microsoftonline.com/common/oauth2/"
                 "v2.0/token")
    userinfo_url = "https://graph.microsoft.com/oidc/userinfo"


class DatatrackerProvider(OIDCProvider):
    name = "datatracker"
    authorize_url = "https://datatracker.ietf.org/oauth/authorize/"
    token_url = "https://datatracker.ietf.org/oauth/token/"
    userinfo_url = "https://datatracker.ietf.org/oauth/userinfo/"


class MockProvider(OIDCProvider):
    """In-process provider: any code of the form ``mock:<email>``
    exchanges successfully. Test backbone."""

    name = "mock"
    authorize_url = "mock://authorize"

    def exchange_code(self, code: str, code_verifier: str):
        if not code.startswith("mock:"):
            raise AuthError("mock code must be 'mock:<email>'")
        return {"access_token": code}

    def fetch_userinfo(self, access_token: str):
        email = access_token.split(":", 1)[1]
        return {"email": email, "sub": email,
                "name": email.split("@")[0]}


PROVIDERS = {cls.name: cls for cls in
             (GitHubProvider, GoogleProvider, MicrosoftProvider,
              DatatrackerProvider, MockProvider)}


def create_oidc_provider(config: Any = None, **kwargs: Any) -> OIDCProvider:
    cfg = dict(config or {})
    driver = cfg.get("driver", "mock")
    cls = PROVIDERS.get(driver)
    if cls is None:
        raise ValueError(f"unknown oidc provider {driver!r}")
    return cls(client_id=cfg.get("client_id", ""),
               client_secret=cfg.get("client_secret", ""),
               redirect_uri=cfg.get("redirect_uri", ""))


# ---------------------------------------------------------------------------
# Role store (reference auth/app/role_store.py)
# ---------------------------------------------------------------------------


class RoleStore:
    COLLECTION = "user_roles"

    def __init__(self, document_store, default_role: str = "reader"):
        self.store = document_store
        self.default_role = default_role

    def roles_for(self, email: str) -> list[str]:
        doc = self.store.get_document(self.COLLECTION, email)
        if doc is None:
            return [self.default_role] if self.default_role else []
        return list(doc.get("roles", []))

    def assign(self, email: str, roles: list[str]) -> None:
        bad = set(roles) - set(ROLES)
        if bad:
            raise AuthError(f"unknown roles: {sorted(bad)}")
        self.store.upsert_document(self.COLLECTION,
                                   {"_id": email, "email": email,
                                    "roles": sorted(set(roles))})

    def remove(self, email: str) -> bool:
        return self.store.delete_document(self.COLLECTION, email)

    def list_users(self) -> list[dict]:
        return self.store.query_documents(self.COLLECTION, {})


# ---------------------------------------------------------------------------
# Auth service
# ---------------------------------------------------------------------------


class AuthService:
    #: Hard cap on concurrently-pending login states; beyond this the
    #: oldest-expiring entries are evicted (unauthenticated /auth/login
    #: floods must not grow memory without bound).
    MAX_PENDING = 10_000
    #: Document collections for token revocation (logout) and the
    #: role-assignment request workflow (reference auth/main.py:787).
    REVOKED = "revoked_tokens"
    ASSIGNMENTS = "pending_assignments"

    def __init__(self, jwt_manager: JWTManager, role_store: RoleStore,
                 providers: dict[str, OIDCProvider] | None = None,
                 login_ttl_seconds: int = 600,
                 max_session_seconds: int = 8 * 3600,
                 service_accounts: dict[str, dict] | None = None):
        self.jwt = jwt_manager
        self.roles = role_store
        # No silent mock default: the mock provider exchanges any
        # `mock:<email>` code for a valid identity, so it must be passed
        # in explicitly (the bootstrap layer gates it behind
        # auth.allow_insecure_mock when enforcement is on).
        self.providers = dict(providers or {})
        self.login_ttl_seconds = login_ttl_seconds
        #: silent refresh works until the ORIGINAL login is this old —
        #: sessions slide within it, then re-authenticate (reference
        #: auth/main.py:325 refresh semantics).
        self.max_session_seconds = max_session_seconds
        #: machine clients for /auth/token client-credentials mint
        #: (reference auth/main.py:494): {client_id: {secret, roles}}.
        self.service_accounts = dict(service_accounts or {})
        self._pending: dict[str, dict[str, Any]] = {}  # state → login ctx
        # HTTPServer is threaded; prune iterates while callbacks pop.
        self._pending_lock = threading.Lock()
        #: callbacks run with the jti on every local revocation — the
        #: JWT middleware registers its cache invalidator here so an
        #: in-process logout takes effect on the very next request.
        self.on_revoke: list[Any] = []

    def _prune_pending_locked(self) -> None:
        now = time.time()
        for state in [s for s, c in self._pending.items()
                      if c["expires"] < now]:
            del self._pending[state]
        while len(self._pending) >= self.MAX_PENDING:
            oldest = min(self._pending, key=lambda s:
                         self._pending[s]["expires"])
            del self._pending[oldest]

    def initiate_login(self, provider: str = "mock") -> dict[str, str]:
        prov = self.providers.get(provider)
        if prov is None:
            raise AuthError(f"unknown provider {provider!r}")
        state = pysecrets.token_urlsafe(24)
        nonce = pysecrets.token_urlsafe(16)
        verifier = pysecrets.token_urlsafe(48)
        challenge = base64.urlsafe_b64encode(
            hashlib.sha256(verifier.encode()).digest()
        ).rstrip(b"=").decode()
        with self._pending_lock:
            self._prune_pending_locked()
            self._pending[state] = {
                "provider": provider, "verifier": verifier, "nonce": nonce,
                "expires": time.time() + self.login_ttl_seconds,
            }
        return {"state": state,
                "authorize_url": prov.build_authorize_url(
                    state, nonce, challenge)}

    def handle_callback(self, state: str, code: str) -> dict[str, Any]:
        with self._pending_lock:
            ctx = self._pending.pop(state, None)
        if ctx is None or ctx["expires"] < time.time():
            raise AuthError("unknown or expired login state")
        prov = self.providers[ctx["provider"]]
        tokens = prov.exchange_code(code, ctx["verifier"])
        info = prov.fetch_userinfo(tokens.get("access_token", ""))
        email = info.get("email") or info.get("sub") or ""
        if not email:
            raise AuthError("provider returned no identity")
        roles = self.roles.roles_for(email)
        token = self.jwt.mint(email, roles=roles,
                              extra_claims={"provider": prov.name,
                                            "name": info.get("name", ""),
                                            "auth_time": int(time.time())})
        return {"access_token": token, "token_type": "Bearer",
                "email": email, "roles": roles}

    def validate_token(self, token: str) -> dict[str, Any]:
        try:
            claims = self.jwt.verify(token)
        except JWTError as exc:
            raise AuthError(str(exc)) from exc
        if self.is_revoked(claims.get("jti", "")):
            raise AuthError("token revoked")
        return claims

    def get_jwks(self) -> dict[str, Any]:
        return self.jwt.jwks()

    # -- token lifecycle (reference auth/main.py:325,460,494) ----------

    def refresh_token(self, token: str) -> dict[str, Any]:
        """Silent refresh: a still-valid token mints a successor with a
        fresh ``exp`` (and freshly-read roles, so role changes
        propagate), until the original login exceeds
        ``max_session_seconds``."""
        claims = self.validate_token(token)
        auth_time = int(claims.get("auth_time") or claims.get("iat", 0))
        if time.time() - auth_time > self.max_session_seconds:
            raise AuthError("session too old; re-authenticate")
        email = claims["sub"]
        roles = (claims.get("roles", []) if claims.get("svc")
                 else self.roles.roles_for(email))
        extra = {"auth_time": auth_time}
        for k in ("provider", "name", "svc"):
            if k in claims:
                extra[k] = claims[k]
        token = self.jwt.mint(email, roles=roles, extra_claims=extra)
        return {"access_token": token, "token_type": "Bearer",
                "email": email, "roles": roles}

    def logout(self, token: str) -> None:
        """Revoke the token's ``jti`` until its natural expiry. Uses the
        document store so every pipeline process sees the revocation."""
        claims = self.validate_token(token)
        self.roles.store.upsert_document(self.REVOKED, {
            "_id": claims.get("jti", ""),
            "exp": int(claims.get("exp", time.time() + 3600)),
        })
        for cb in self.on_revoke:
            cb(claims.get("jti", ""))
        # Opportunistic prune: entries past their exp can never match
        # again (verify() rejects expired tokens first), so each logout
        # also clears the dead ones — the collection stays bounded by
        # live-token count instead of growing one row per logout ever.
        now = time.time()
        for doc in self.roles.store.query_documents(
                self.REVOKED, {"exp": {"$lt": now}}):
            self.roles.store.delete_document(self.REVOKED, doc["_id"])

    def is_revoked(self, jti: str) -> bool:
        if not jti:
            return False
        doc = self.roles.store.get_document(self.REVOKED, jti)
        return doc is not None and time.time() <= doc.get("exp", 0)

    def mint_service_token(self, client_id: str,
                           client_secret: str) -> dict[str, Any]:
        """Client-credentials mint for machine callers (retry jobs,
        exporters, cross-service calls) — reference auth/main.py:494."""
        acct = self.service_accounts.get(client_id)
        if acct is None or not _consteq(acct.get("secret", ""),
                                        client_secret):
            raise AuthError("invalid client credentials")
        roles = list(acct.get("roles", []))
        token = self.jwt.mint(
            f"svc:{client_id}", roles=roles,
            extra_claims={"svc": True, "auth_time": int(time.time())})
        return {"access_token": token, "token_type": "Bearer",
                "roles": roles}

    # -- role-assignment workflow (reference auth/main.py:787,1074) ----

    def request_roles(self, email: str, roles: list[str],
                      note: str = "") -> dict[str, Any]:
        bad = set(roles) - set(ROLES)
        if bad:
            raise AuthError(f"unknown roles: {sorted(bad)}")
        if not roles:
            raise AuthError("no roles requested")
        doc = {
            "_id": f"{email}:{','.join(sorted(roles))}",
            "email": email, "roles": sorted(set(roles)), "note": note,
            "status": "pending", "requested_at": int(time.time()),
        }
        self.roles.store.upsert_document(self.ASSIGNMENTS, doc)
        return doc

    def list_pending_assignments(self) -> list[dict]:
        return self.roles.store.query_documents(
            self.ASSIGNMENTS, {"status": "pending"})

    def resolve_assignment(self, assignment_id: str, approve: bool,
                           decided_by: str) -> dict[str, Any]:
        doc = self.roles.store.get_document(self.ASSIGNMENTS,
                                            assignment_id)
        if doc is None or doc.get("status") != "pending":
            raise AuthError("no such pending assignment")
        doc["status"] = "approved" if approve else "denied"
        doc["decided_by"] = decided_by
        doc["decided_at"] = int(time.time())
        if approve:
            merged = sorted(set(self.roles.roles_for(doc["email"]))
                            | set(doc["roles"]))
            self.roles.assign(doc["email"], merged)
        self.roles.store.upsert_document(self.ASSIGNMENTS, doc)
        return doc


def _consteq(a: str, b: str) -> bool:
    import hmac
    return hmac.compare_digest(a.encode(), b.encode())


# ---------------------------------------------------------------------------
# HTTP middleware (reference copilot_auth/middleware.py:52,588)
# ---------------------------------------------------------------------------

PUBLIC_PATHS = ("/health", "/readyz", "/metrics", "/auth/login",
                "/auth/callback", "/auth/token",
                "/.well-known/jwks.json",
                "/.well-known/openid-configuration",
                # The SPA shell and its assets are public; every API call
                # the SPA makes still carries the bearer token.
                "/", "/ui", "/api/openapi.json")


def is_public_path(path: str, public_paths=PUBLIC_PATHS) -> bool:
    """Exact or path-segment-boundary match only: /metrics is public, a
    hypothetical /metrics-private must not be. The ONE definition shared
    by the enforcing middleware and the OpenAPI generator, so the spec
    cannot drift from behavior."""
    return any(path == p or path.startswith(p + "/") for p in public_paths)


def create_jwt_middleware(jwt_manager: JWTManager,
                          required_roles: dict[str, list[str]]
                          | None = None,
                          public_paths=PUBLIC_PATHS,
                          is_revoked=None,
                          revocation_cache_ttl: float = 0.0):
    """Router middleware: verifies Bearer tokens, stamps claims into
    ``req.context``, enforces per-path-prefix role requirements.
    ``is_revoked(jti) -> bool`` plugs the logout denylist in — a
    logged-out token must fail even though its signature still
    verifies.

    Revocation results can be cached per-jti for
    ``revocation_cache_ttl`` seconds: with a remote document store
    behind ``is_revoked`` (e.g. the Cosmos driver) an uncached check
    adds an HTTP round-trip to every API call. A revoked verdict is
    cached forever (tokens don't un-revoke); a clean verdict only for
    the TTL, which bounds the post-logout acceptance window.

    The cache defaults OFF (ttl=0): caching weakens cross-replica
    logout — a token revoked on another replica stays accepted here
    for up to the TTL — so deployments must opt in explicitly (the
    ``auth.revocation_cache_ttl`` config key) after weighing that
    window against the per-request store round-trip."""
    required_roles = required_roles or {}
    # jti -> (expires_at_monotonic, revoked)
    _revocation_cache: dict[str, tuple[float, bool]] = {}
    _cache_lock = threading.Lock()
    # bumped by invalidate(); a clean verdict computed against the store
    # BEFORE an invalidation must not be written back AFTER it (TOCTOU:
    # the revoked token would be accepted for a full TTL in the very
    # process that performed the logout)
    _generation = [0]

    def _check_revoked(jti: str) -> bool:
        if revocation_cache_ttl <= 0:
            return bool(is_revoked(jti))
        now = time.monotonic()
        with _cache_lock:
            hit = _revocation_cache.get(jti)
            if hit is not None and (hit[1] or hit[0] > now):
                return hit[1]
            gen = _generation[0]
        revoked = bool(is_revoked(jti))
        with _cache_lock:
            if len(_revocation_cache) > 10000:   # bound memory
                cutoff = time.monotonic()
                for k in [k for k, (exp, rv) in _revocation_cache.items()
                          if not rv and exp <= cutoff]:
                    del _revocation_cache[k]
                if len(_revocation_cache) > 10000:
                    _revocation_cache.clear()
            if revoked or _generation[0] == gen:
                _revocation_cache[jti] = (now + revocation_cache_ttl,
                                          revoked)
        return revoked

    def middleware(req: Request) -> None:
        if is_public_path(req.path, public_paths):
            return
        header = req.headers.get("Authorization") or req.headers.get(
            "authorization") or ""
        if not header.startswith("Bearer "):
            raise HTTPError(401, "missing bearer token")
        try:
            claims = jwt_manager.verify(header[7:])
        except JWTError as exc:
            raise HTTPError(401, f"invalid token: {exc}")
        if is_revoked is not None and _check_revoked(claims.get("jti", "")):
            raise HTTPError(401, "token revoked")
        req.context.update(claims)
        roles = set(claims.get("roles", []))
        for prefix, needed in required_roles.items():
            if req.path.startswith(prefix):
                if not roles.intersection(needed):
                    raise HTTPError(
                        403, f"requires one of roles {needed}")
                break

    def invalidate(jti: str) -> None:
        """Drop a jti's cached verdict — wired to the local logout path
        so in-process revocation is immediate; the TTL only bounds
        revocations performed by OTHER replicas."""
        with _cache_lock:
            _revocation_cache.pop(jti, None)
            _generation[0] += 1

    middleware.invalidate = invalidate
    return middleware


def auth_router(service: AuthService, external_base_url: str | None = None):
    """Auth HTTP surface (reference ``auth/main.py:115-1074``).

    ``external_base_url`` is the deployment's public https base; when set
    the discovery document advertises it instead of trusting the
    client-controlled Host / X-Forwarded-Proto headers (which, behind a
    cache or misconfigured proxy, allow discovery-document poisoning)."""
    from copilot_for_consensus_tpu.services.http import Router

    router = Router()

    @router.get("/auth/login")
    def login(req):
        try:
            return service.initiate_login(req.query.get("provider", "mock"))
        except AuthError as exc:
            raise HTTPError(400, str(exc))

    @router.get("/auth/callback")
    def callback(req):
        state = req.query.get("state", "")
        code = req.query.get("code", "")
        try:
            return service.handle_callback(state, code)
        except AuthError as exc:
            raise HTTPError(401, str(exc))

    @router.get("/auth/userinfo")
    def userinfo(req):
        header = req.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            raise HTTPError(401, "missing bearer token")
        try:
            claims = service.validate_token(header[7:])
        except AuthError as exc:
            raise HTTPError(401, str(exc))
        return {"sub": claims.get("sub"), "roles": claims.get("roles"),
                "provider": claims.get("provider")}

    @router.get("/.well-known/jwks.json")
    def jwks(req):
        return service.get_jwks()

    @router.get("/.well-known/openid-configuration")
    def openid_configuration(req):
        """OIDC discovery document for edge-gateway JWT validation.

        Gateways like Azure APIM's validate-jwt resolve signing keys via
        discovery rather than a raw JWKS URL; strict consumers also
        require the authorization/token endpoints and standard response
        types, so the full REQUIRED metadata set is advertised."""
        if external_base_url:
            base = external_base_url.rstrip("/")
        else:
            # Unconfigured (dev) deployments fall back to the request
            # headers; production should set auth.external_base_url.
            host = (req.headers.get("host") or req.headers.get("Host")
                    or "localhost")
            # Behind the TLS edge the advertised URLs must be https — the
            # generated nginx config forwards the original scheme.
            proto = (req.headers.get("x-forwarded-proto")
                     or req.headers.get("X-Forwarded-Proto") or "http")
            base = f"{proto}://{host}"
        return {
            "issuer": service.jwt.issuer,
            "authorization_endpoint": f"{base}/auth/login",
            "token_endpoint": f"{base}/auth/callback",
            "jwks_uri": f"{base}/.well-known/jwks.json",
            "id_token_signing_alg_values_supported": ["RS256"],
            "response_types_supported": ["code", "id_token"],
            "subject_types_supported": ["public"],
        }

    @router.post("/auth/refresh")
    def refresh(req):
        """Silent refresh (reference auth/main.py:325): a valid bearer
        mints a successor with fresh exp + freshly-read roles."""
        try:
            return service.refresh_token(_bearer(req))
        except AuthError as exc:
            raise HTTPError(401, str(exc))

    @router.post("/auth/logout")
    def logout(req):
        """Revoke the presented token until its natural expiry
        (reference auth/main.py:460)."""
        try:
            service.logout(_bearer(req))
        except AuthError as exc:
            raise HTTPError(401, str(exc))
        return {"status": "logged_out"}

    @router.post("/auth/token")
    def service_token(req):
        """Client-credentials mint for machine callers (reference
        auth/main.py:494). Body: {client_id, client_secret}."""
        body = req.json()
        if not isinstance(body, dict):
            raise HTTPError(400, "body must be an object")
        try:
            return service.mint_service_token(
                str(body.get("client_id", "")),
                str(body.get("client_secret", "")))
        except AuthError as exc:
            raise HTTPError(401, str(exc))

    @router.post("/auth/roles/request")
    def request_roles(req):
        """Any authenticated user may request roles; admins approve or
        deny (reference auth/main.py:787)."""
        claims = _authed(req, service)
        body = req.json()
        if not isinstance(body, dict) or "roles" not in body:
            raise HTTPError(400, "body must have roles")
        try:
            return service.request_roles(claims["sub"], body["roles"],
                                         note=str(body.get("note", "")))
        except AuthError as exc:
            raise HTTPError(400, str(exc))

    @router.get("/auth/admin/pending")
    def list_pending(req):
        _require_admin(req, service)
        return {"pending": service.list_pending_assignments()}

    @router.post("/auth/admin/pending/{assignment_id}")
    def resolve_pending(req):
        """Approve/deny a pending assignment (reference
        auth/main.py:1074). Body: {action: "approve"|"deny"}."""
        claims = _require_admin(req, service)
        body = req.json()
        # a valid-JSON but non-object body (e.g. a bare string — found
        # by the r5 deep fuzz run) is a 400, not an AttributeError 500
        if not isinstance(body, dict):
            raise HTTPError(400, "body must be an object")
        action = body.get("action", "")
        if action not in ("approve", "deny"):
            raise HTTPError(400, "action must be approve|deny")
        try:
            return service.resolve_assignment(
                req.params["assignment_id"], action == "approve",
                decided_by=claims.get("sub", ""))
        except AuthError as exc:
            raise HTTPError(404, str(exc))

    @router.get("/auth/admin/users")
    def list_users(req):
        _require_admin(req, service)
        return {"users": service.roles.list_users()}

    @router.put("/auth/admin/users/{email}")
    def assign_roles(req):
        _require_admin(req, service)
        body = req.json()
        if not isinstance(body, dict) or "roles" not in body:
            raise HTTPError(400, "body must have roles")
        try:
            service.roles.assign(req.params["email"], body["roles"])
        except AuthError as exc:
            raise HTTPError(400, str(exc))
        return {"email": req.params["email"],
                "roles": service.roles.roles_for(req.params["email"])}

    @router.delete("/auth/admin/users/{email}")
    def remove_user(req):
        _require_admin(req, service)
        if not service.roles.remove(req.params["email"]):
            raise HTTPError(404, "user not found")
        return {"status": "removed"}

    return router


def _bearer(req: Request) -> str:
    header = req.headers.get("Authorization") or req.headers.get(
        "authorization") or ""
    if not header.startswith("Bearer "):
        raise HTTPError(401, "missing bearer token")
    return header[7:]


def _authed(req: Request, service: AuthService) -> dict[str, Any]:
    try:
        return service.validate_token(_bearer(req))
    except AuthError as exc:
        raise HTTPError(401, str(exc))


def _require_admin(req: Request, service: AuthService) -> dict[str, Any]:
    claims = _authed(req, service)
    if "admin" not in claims.get("roles", []):
        raise HTTPError(403, "admin role required")
    return claims
