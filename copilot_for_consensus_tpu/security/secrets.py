"""Secret providers.

Parity with the reference's ``copilot_secrets`` (ABC + local file provider +
cloud provider + factory). ``secret://name`` references inside configs are
resolved through one of these at config load time (core/config.py).
"""

from __future__ import annotations

import abc
import os
import pathlib
from typing import Callable, Mapping


class SecretNotFoundError(KeyError):
    pass


class SecretProvider(abc.ABC):
    @abc.abstractmethod
    def get_secret(self, name: str) -> str:
        """Return the secret value or raise SecretNotFoundError."""

    def __call__(self, name: str) -> str:
        return self.get_secret(name)


class LocalSecretProvider(SecretProvider):
    """Secrets as individual files in a directory (``secrets/<name>``)."""

    def __init__(self, root: str | pathlib.Path):
        self.root = pathlib.Path(root)

    def get_secret(self, name: str) -> str:
        if "/" in name or "\\" in name or name.startswith("."):
            raise SecretNotFoundError(name)
        path = self.root / name
        if not path.is_file():
            raise SecretNotFoundError(name)
        return path.read_text().strip()


class EnvSecretProvider(SecretProvider):
    """Secrets from ``COPILOT_SECRET_<NAME>`` environment variables."""

    def __init__(self, env: Mapping[str, str] | None = None):
        self.env = os.environ if env is None else env

    def get_secret(self, name: str) -> str:
        key = f"COPILOT_SECRET_{name.upper()}"
        if key not in self.env:
            raise SecretNotFoundError(name)
        return self.env[key]


class StaticSecretProvider(SecretProvider):
    """In-memory secrets for tests."""

    def __init__(self, values: Mapping[str, str]):
        self.values = dict(values)

    def get_secret(self, name: str) -> str:
        try:
            return self.values[name]
        except KeyError:
            raise SecretNotFoundError(name) from None


class ChainSecretProvider(SecretProvider):
    def __init__(self, *providers: SecretProvider):
        self.providers = providers

    def get_secret(self, name: str) -> str:
        for p in self.providers:
            try:
                return p.get_secret(name)
            except SecretNotFoundError:
                continue
        raise SecretNotFoundError(name)


def default_secret_resolver(env: Mapping[str, str] | None = None) -> Callable[[str], str]:
    """Env secrets first, then files under $COPILOT_SECRETS_DIR (or ./secrets)."""
    env = os.environ if env is None else env
    secrets_dir = env.get("COPILOT_SECRETS_DIR", "secrets")
    return ChainSecretProvider(
        EnvSecretProvider(env), LocalSecretProvider(secrets_dir)
    )


class AzureKeyVaultSecretProvider(SecretProvider):
    """Azure Key Vault secrets via raw REST — no SDK (reference
    ``copilot_secrets/azurekeyvault_provider.py`` rides the SDK).

    AAD client-credentials flow mints the bearer token
    (``POST {authority}/{tenant}/oauth2/v2.0/token``), cached until
    shortly before expiry; secrets read via
    ``GET {vault}/secrets/{name}?api-version=7.4``. ``authority`` and
    ``vault_url`` overrides point the provider at mocks/emulators —
    how ``tests/test_azure_drivers.py`` exercises the wire contract in
    this zero-egress image.
    """

    API_VERSION = "7.4"

    def __init__(self, vault_url: str, tenant_id: str, client_id: str,
                 client_secret: str,
                 authority: str = "https://login.microsoftonline.com",
                 timeout_s: float = 15.0):
        if not all((vault_url, tenant_id, client_id, client_secret)):
            raise ValueError(
                "azure_keyvault needs vault_url, tenant_id, client_id, "
                "client_secret")
        self.vault_url = vault_url.rstrip("/")
        self.tenant_id = tenant_id
        self.client_id = client_id
        self.client_secret = client_secret
        self.authority = authority.rstrip("/")
        self.timeout_s = timeout_s
        self._token: str | None = None
        self._token_exp = 0.0

    def _bearer(self) -> str:
        import json
        import time
        import urllib.parse
        import urllib.request

        if self._token and time.time() < self._token_exp - 60:
            return self._token
        scope = f"{self.vault_url}/.default"
        body = urllib.parse.urlencode({
            "grant_type": "client_credentials",
            "client_id": self.client_id,
            "client_secret": self.client_secret,
            "scope": scope,
        }).encode()
        req = urllib.request.Request(
            f"{self.authority}/{self.tenant_id}/oauth2/v2.0/token",
            data=body, method="POST",
            headers={"Content-Type":
                     "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            tok = json.loads(resp.read())
        self._token = tok["access_token"]
        self._token_exp = time.time() + float(tok.get("expires_in", 300))
        return self._token

    def get_secret(self, name: str) -> str:
        import json
        import urllib.error
        import urllib.request

        if not name or not all(
                (c.isascii() and c.isalnum()) or c == "-"
                for c in name):
            raise SecretNotFoundError(name)   # KV's own name charset
        try:
            bearer = self._bearer()
        except urllib.error.HTTPError as exc:
            raise RuntimeError(
                f"key vault token request failed: "
                f"HTTP {exc.code}") from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise RuntimeError(
                f"key vault token endpoint unreachable: {exc}") from exc
        req = urllib.request.Request(
            f"{self.vault_url}/secrets/{name}"
            f"?api-version={self.API_VERSION}",
            headers={"Authorization": f"Bearer {bearer}"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return str(json.loads(resp.read())["value"])
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise SecretNotFoundError(name) from exc
            raise RuntimeError(
                f"key vault GET {name} failed: HTTP {exc.code}") from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise RuntimeError(
                f"key vault unreachable: {exc}") from exc


def create_secret_provider(config=None) -> SecretProvider:
    """Config-driven construction: env / local / static / chain-default
    / azure_keyvault."""
    cfg = dict(config or {})
    # 'env' stays the implicit default (the pre-r3 factory behavior):
    # silently adding the local-file fallback could resolve a stale
    # on-disk secret that the environment deliberately omits.
    driver = cfg.get("driver", "env")
    if driver == "default":
        if cfg.get("root"):
            return ChainSecretProvider(
                EnvSecretProvider(),
                LocalSecretProvider(cfg["root"]))
        # the SAME chain config-load-time secret:// resolution uses —
        # including its COPILOT_SECRETS_DIR handling
        return default_secret_resolver()
    if driver == "env":
        return EnvSecretProvider()
    if driver == "local":
        return LocalSecretProvider(cfg.get("root", "secrets"))
    if driver == "static":
        return StaticSecretProvider(cfg.get("values", {}))
    if driver == "azure_keyvault":
        return AzureKeyVaultSecretProvider(
            vault_url=cfg.get("vault_url", ""),
            tenant_id=cfg.get("tenant_id", ""),
            client_id=cfg.get("client_id", ""),
            client_secret=cfg.get("client_secret", ""),
            authority=cfg.get("authority",
                              "https://login.microsoftonline.com"))
    raise ValueError(f"unknown secrets driver {driver!r}")
