"""Secret providers.

Parity with the reference's ``copilot_secrets`` (ABC + local file provider +
cloud provider + factory). ``secret://name`` references inside configs are
resolved through one of these at config load time (core/config.py).
"""

from __future__ import annotations

import abc
import os
import pathlib
from typing import Callable, Mapping


class SecretNotFoundError(KeyError):
    pass


class SecretProvider(abc.ABC):
    @abc.abstractmethod
    def get_secret(self, name: str) -> str:
        """Return the secret value or raise SecretNotFoundError."""

    def __call__(self, name: str) -> str:
        return self.get_secret(name)


class LocalSecretProvider(SecretProvider):
    """Secrets as individual files in a directory (``secrets/<name>``)."""

    def __init__(self, root: str | pathlib.Path):
        self.root = pathlib.Path(root)

    def get_secret(self, name: str) -> str:
        if "/" in name or "\\" in name or name.startswith("."):
            raise SecretNotFoundError(name)
        path = self.root / name
        if not path.is_file():
            raise SecretNotFoundError(name)
        return path.read_text().strip()


class EnvSecretProvider(SecretProvider):
    """Secrets from ``COPILOT_SECRET_<NAME>`` environment variables."""

    def __init__(self, env: Mapping[str, str] | None = None):
        self.env = os.environ if env is None else env

    def get_secret(self, name: str) -> str:
        key = f"COPILOT_SECRET_{name.upper()}"
        if key not in self.env:
            raise SecretNotFoundError(name)
        return self.env[key]


class StaticSecretProvider(SecretProvider):
    """In-memory secrets for tests."""

    def __init__(self, values: Mapping[str, str]):
        self.values = dict(values)

    def get_secret(self, name: str) -> str:
        try:
            return self.values[name]
        except KeyError:
            raise SecretNotFoundError(name) from None


class ChainSecretProvider(SecretProvider):
    def __init__(self, *providers: SecretProvider):
        self.providers = providers

    def get_secret(self, name: str) -> str:
        for p in self.providers:
            try:
                return p.get_secret(name)
            except SecretNotFoundError:
                continue
        raise SecretNotFoundError(name)


def default_secret_resolver(env: Mapping[str, str] | None = None) -> Callable[[str], str]:
    """Env secrets first, then files under $COPILOT_SECRETS_DIR (or ./secrets)."""
    env = os.environ if env is None else env
    secrets_dir = env.get("COPILOT_SECRETS_DIR", "secrets")
    return ChainSecretProvider(
        EnvSecretProvider(env), LocalSecretProvider(secrets_dir)
    )
