"""JWT mint/verify (RS256 + JWKS) and signing backends.

Parity with the reference's ``copilot_auth/jwt_manager.py:35`` (mint /
verify RS256 with JWKS publication) and ``copilot_jwt_signer`` (signer
ABC with local-PEM and KMS drivers). Implemented on ``cryptography``
directly — no PyJWT in the image, and the JWS subset needed (RS256/HS256
compact serialization) is small enough to own.
"""

from __future__ import annotations

import abc
import base64
import hashlib
import hmac as hmac_mod
import json
import time
import uuid
from typing import Any

try:  # RSA signers need it; HS256 and token plumbing do not
    import cryptography  # noqa: F401 (probe only; real imports are lazy)

    HAS_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - environment without the wheel
    HAS_CRYPTOGRAPHY = False


class JWTError(Exception):
    pass


def require_cryptography(feature: str) -> None:
    """Fail with an actionable error (not a bare ModuleNotFoundError
    deep in a lazy import) when an RSA feature is used without the
    optional ``cryptography`` dependency installed."""
    if not HAS_CRYPTOGRAPHY:
        raise JWTError(
            f"{feature} requires the optional 'cryptography' package "
            "(RSA primitives); install it or configure the hs256 "
            "shared-secret signer instead")


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def _b64url_uint(n: int) -> str:
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return _b64url(raw)


# ---------------------------------------------------------------------------
# Signers (reference: copilot_jwt_signer)
# ---------------------------------------------------------------------------


class JWTSigner(abc.ABC):
    alg: str = ""
    kid: str = ""

    @abc.abstractmethod
    def sign(self, signing_input: bytes) -> bytes: ...

    @abc.abstractmethod
    def verify(self, signing_input: bytes, signature: bytes) -> bool: ...

    def public_jwk(self) -> dict[str, Any] | None:
        return None


class LocalRS256Signer(JWTSigner):
    """RSA keypair signer (reference ``local_signer.py``): generates a
    keypair on first use or loads PEM from disk/secret."""

    alg = "RS256"

    def __init__(self, private_pem: bytes | str | None = None,
                 key_size: int = 2048):
        require_cryptography("the local_rs256 signer")
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.hazmat.primitives.serialization import (
            load_pem_private_key,
        )

        if private_pem:
            pem = (private_pem.encode() if isinstance(private_pem, str)
                   else private_pem)
            self._key = load_pem_private_key(pem, password=None)
        else:
            self._key = rsa.generate_private_key(
                public_exponent=65537, key_size=key_size)
        pub = self._key.public_key().public_numbers()
        digest = hashlib.sha256(
            f"{pub.n:x}:{pub.e:x}".encode()).hexdigest()
        self.kid = digest[:16]

    def private_pem(self) -> bytes:
        from cryptography.hazmat.primitives import serialization
        return self._key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())

    def sign(self, signing_input: bytes) -> bytes:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        return self._key.sign(signing_input, padding.PKCS1v15(),
                              hashes.SHA256())

    def verify(self, signing_input: bytes, signature: bytes) -> bool:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        try:
            self._key.public_key().verify(
                signature, signing_input, padding.PKCS1v15(),
                hashes.SHA256())
            return True
        except InvalidSignature:
            return False

    def public_jwk(self) -> dict[str, Any]:
        pub = self._key.public_key().public_numbers()
        return {"kty": "RSA", "use": "sig", "alg": "RS256",
                "kid": self.kid, "n": _b64url_uint(pub.n),
                "e": _b64url_uint(pub.e)}


class HS256Signer(JWTSigner):
    """Shared-secret HMAC signer (single-tenant deployments/tests)."""

    alg = "HS256"

    def __init__(self, secret: str | bytes):
        self._secret = secret.encode() if isinstance(secret, str) else secret
        self.kid = hashlib.sha256(self._secret).hexdigest()[:16]

    def sign(self, signing_input: bytes) -> bytes:
        return hmac_mod.new(self._secret, signing_input,
                            hashlib.sha256).digest()

    def verify(self, signing_input: bytes, signature: bytes) -> bool:
        return hmac_mod.compare_digest(self.sign(signing_input), signature)


def create_jwt_signer(config: Any = None, **kwargs: Any) -> JWTSigner:
    cfg = dict(config or {})
    driver = cfg.get("driver", "local_rs256")
    if driver == "local_rs256":
        return LocalRS256Signer(private_pem=cfg.get("private_pem")
                                or kwargs.get("private_pem"))
    if driver == "hs256":
        secret = cfg.get("secret") or kwargs.get("secret")
        if not secret:
            raise ValueError("hs256 signer needs a secret")
        return HS256Signer(secret)
    if driver == "azure_keyvault":
        from copilot_for_consensus_tpu.security.keyvault_signer import (
            AzureKeyVaultSigner,
        )

        return AzureKeyVaultSigner(
            cfg.get("vault_url", ""), cfg.get("key_name", ""),
            cfg.get("tenant_id", ""), cfg.get("client_id", ""),
            cfg.get("client_secret", ""),
            key_version=cfg.get("key_version", ""),
            authority=cfg.get("authority",
                              "https://login.microsoftonline.com"))
    raise ValueError(f"unknown jwt_signer driver {driver!r}")


# ---------------------------------------------------------------------------
# JWT manager (reference: copilot_auth/jwt_manager.py:35)
# ---------------------------------------------------------------------------


class JWTManager:
    def __init__(self, signer: JWTSigner, issuer: str = "copilot",
                 audience: str = "copilot-api",
                 ttl_seconds: int = 3600):
        self.signer = signer
        self.issuer = issuer
        self.audience = audience
        self.ttl_seconds = ttl_seconds

    def mint(self, subject: str, roles: list[str] | None = None,
             extra_claims: dict[str, Any] | None = None,
             ttl_seconds: int | None = None) -> str:
        now = int(time.time())
        claims = {
            "iss": self.issuer, "aud": self.audience, "sub": subject,
            "iat": now, "exp": now + (ttl_seconds or self.ttl_seconds),
            "jti": uuid.uuid4().hex, "roles": roles or [],
            **(extra_claims or {}),
        }
        header = {"alg": self.signer.alg, "typ": "JWT",
                  "kid": self.signer.kid}
        signing_input = (
            _b64url(json.dumps(header, separators=(",", ":")).encode())
            + "." +
            _b64url(json.dumps(claims, separators=(",", ":")).encode())
        ).encode()
        sig = self.signer.sign(signing_input)
        return signing_input.decode() + "." + _b64url(sig)

    def verify(self, token: str, *, verify_aud: bool = True
               ) -> dict[str, Any]:
        """Returns the claims; raises JWTError on any failure."""
        parts = token.split(".")
        if len(parts) != 3:
            raise JWTError("malformed token")
        signing_input = (parts[0] + "." + parts[1]).encode()
        try:
            header = json.loads(_b64url_decode(parts[0]))
            claims = json.loads(_b64url_decode(parts[1]))
            sig = _b64url_decode(parts[2])
        except Exception as exc:
            raise JWTError(f"undecodable token: {exc}") from exc
        if header.get("alg") != self.signer.alg:
            raise JWTError(
                f"algorithm mismatch: {header.get('alg')}")
        if not self.signer.verify(signing_input, sig):
            raise JWTError("signature verification failed")
        now = time.time()
        if claims.get("exp") is not None and now > claims["exp"]:
            raise JWTError("token expired")
        if claims.get("nbf") is not None and now < claims["nbf"]:
            raise JWTError("token not yet valid")
        if claims.get("iss") != self.issuer:
            raise JWTError("issuer mismatch")
        if verify_aud and claims.get("aud") != self.audience:
            raise JWTError("audience mismatch")
        return claims

    def jwks(self) -> dict[str, Any]:
        jwk = self.signer.public_jwk()
        return {"keys": [jwk] if jwk else []}
