"""Driver registration for security adapters: secret providers, JWT
signers, OIDC providers."""

from __future__ import annotations


from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.security.secrets import (
    create_secret_provider,
)

for _name in ("env", "local", "static", "default", "azure_keyvault"):
    register_driver("secret_provider", _name, create_secret_provider)

for _name in ("local_rs256", "hs256"):
    register_driver(
        "jwt_signer", _name,
        "copilot_for_consensus_tpu.security.jwt:create_jwt_signer")

for _name in ("github", "google", "microsoft", "datatracker", "mock"):
    register_driver(
        "oidc_provider", _name,
        "copilot_for_consensus_tpu.security.auth:create_oidc_provider")
