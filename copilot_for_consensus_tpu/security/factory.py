"""Driver registration for security adapters (secret providers now; JWT
signers and OIDC providers register here as they land)."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.security.secrets import (
    EnvSecretProvider,
    LocalSecretProvider,
    StaticSecretProvider,
)


def create_secret_provider(config: Any) -> Any:
    cfg = dict(config or {})
    driver = cfg.get("driver", "env")
    if driver == "env":
        return EnvSecretProvider()
    if driver == "local":
        return LocalSecretProvider(cfg.get("root", "secrets"))
    if driver == "static":
        return StaticSecretProvider(cfg.get("values", {}))
    raise ValueError(f"unknown secret_provider driver {driver!r}")


for _name in ("env", "local", "static"):
    register_driver("secret_provider", _name, create_secret_provider)
