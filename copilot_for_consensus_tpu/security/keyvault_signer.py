"""Azure Key Vault JWT signer — raw REST, no SDK.

Fills the role of the reference's
``copilot_jwt_signer/keyvault_signer.py:102`` (KeyVaultJWTSigner: sign
via Key Vault's ``sign`` operation so the private key NEVER leaves the
vault, JWK/PEM publication from the vault's public half, transient-error
retry behind a circuit breaker). Same driver conventions as the repo's
other Azure adapters: AAD client-credentials bearer (as
``security/secrets.py`` Key Vault provider), endpoint/authority
overrides for the wire-contract mock, stdlib HTTP only.

Wire surface (Key Vault REST 7.4):

* ``GET  {vault}/keys/{name}/{version}`` → public JWK (n, e, kid)
* ``POST {vault}/keys/{name}/{version}/sign`` with
  ``{"alg": "RS256", "value": b64url(sha256(signing_input))}`` →
  ``{"value": b64url(signature)}``

Verification is local against the fetched public key, so token
validation never round-trips to the vault.
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

from copilot_for_consensus_tpu.security.jwt import (
    JWTError,
    JWTSigner,
    require_cryptography,
)

API_VERSION = "7.4"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    return base64.urlsafe_b64decode(data + "=" * (-len(data) % 4))


class CircuitBreaker:
    """Stop hammering the vault after repeated failures (reference
    ``keyvault_signer.py:18``): after ``threshold`` consecutive
    failures the circuit opens for ``cooldown_s`` and calls fail fast;
    one success closes it."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._failures = 0
        self._open_until = 0.0
        self._lock = threading.Lock()

    def call(self, fn, *args, **kwargs):
        with self._lock:
            if time.monotonic() < self._open_until:
                raise JWTError(
                    "key vault circuit open (recent failures); "
                    f"retrying after {self.cooldown_s}s cooldown")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            with self._lock:
                self._failures += 1
                if self._failures >= self.threshold:
                    self._open_until = (time.monotonic()
                                        + self.cooldown_s)
                    self._failures = 0
            raise
        with self._lock:
            self._failures = 0
        return out


class AzureKeyVaultSigner(JWTSigner):
    alg = "RS256"

    def __init__(self, vault_url: str, key_name: str,
                 tenant_id: str, client_id: str, client_secret: str, *,
                 key_version: str = "",
                 authority: str = "https://login.microsoftonline.com",
                 timeout_s: float = 15.0, retry_attempts: int = 2,
                 retry_backoff_s: float = 0.2,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 30.0):
        if not all((vault_url, key_name, tenant_id, client_id,
                    client_secret)):
            raise ValueError(
                "azure_keyvault signer needs vault_url, key_name, "
                "tenant_id, client_id, client_secret")
        self.vault_url = vault_url.rstrip("/")
        self.key_name = key_name
        self.key_version = key_version
        self.authority = authority.rstrip("/")
        self.tenant_id = tenant_id
        self.client_id = client_id
        self.client_secret = client_secret
        self.timeout_s = timeout_s
        self.retry_attempts = retry_attempts
        self.retry_backoff_s = retry_backoff_s
        self.breaker = CircuitBreaker(breaker_threshold,
                                      breaker_cooldown_s)
        self._token: str | None = None
        self._token_exp = 0.0
        self._jwk: dict[str, Any] | None = None
        self._pub = None                      # cryptography public key
        self._kid = ""
        self._lock = threading.Lock()         # guards the AAD token
        self._load_lock = threading.Lock()    # guards key-fetch init

    @property
    def kid(self) -> str:
        """Lazy: JWTManager reads this for the JWT header before the
        first sign, so the vault key must be fetched here too."""
        self._load_public()
        # write-once under _load_lock; _load_public() acquires that
        # lock first, so this read happens-after the load on every
        # thread (guarded-lazy-init publication, not a race)
        # jaxlint: disable=race-unlocked-field
        return self._kid

    # -- AAD bearer (same flow as security/secrets.py Key Vault) -------

    def _bearer(self) -> str:
        with self._lock:
            if self._token and time.time() < self._token_exp - 60:
                return self._token
        body = urllib.parse.urlencode({
            "grant_type": "client_credentials",
            "client_id": self.client_id,
            "client_secret": self.client_secret,
            "scope": f"{self.vault_url}/.default",
        }).encode()
        req = urllib.request.Request(
            f"{self.authority}/{self.tenant_id}/oauth2/v2.0/token",
            data=body, method="POST",
            headers={"Content-Type":
                     "application/x-www-form-urlencoded"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            tok = json.loads(r.read())
        with self._lock:
            self._token = tok["access_token"]
            self._token_exp = time.time() + float(
                tok.get("expires_in", 300))
            return self._token

    def _request(self, method: str, path: str,
                 body: dict | None = None) -> dict:
        url = (f"{self.vault_url}{path}?api-version={API_VERSION}")
        attempt = 0
        while True:
            # the AAD token fetch shares the retry/JWTError envelope:
            # a transient token-endpoint blip must retry, and callers
            # who catch JWTError (JWTManager, auth middleware) must see
            # auth failures in that class, not raw urllib errors
            try:
                req = urllib.request.Request(
                    url, method=method,
                    data=json.dumps(body).encode() if body else None,
                    headers={"Authorization":
                             f"Bearer {self._bearer()}",
                             "Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                transient = exc.code in (408, 429, 500, 502, 503, 504)
                if not (transient and attempt < self.retry_attempts):
                    raise JWTError(
                        f"key vault {method} {path}: HTTP {exc.code} "
                        f"{exc.read()[:120].decode('utf-8', 'replace')}"
                    ) from exc
            except (urllib.error.URLError, TimeoutError, OSError) as exc:
                if attempt >= self.retry_attempts:
                    raise JWTError(
                        f"key vault unreachable: {exc}") from exc
            time.sleep(self.retry_backoff_s * (2 ** attempt))
            attempt += 1

    # -- key material ---------------------------------------------------

    def _key_path(self) -> str:
        version = f"/{self.key_version}" if self.key_version else ""
        return f"/keys/{self.key_name}{version}"

    def _load_public(self) -> None:
        # double-checked under _load_lock; _pub is assigned LAST so a
        # racing reader that sees it non-None also sees _kid/_jwk set
        # (a separate lock from the AAD one — _request → _bearer takes
        # _lock while we hold _load_lock)
        if self._pub is not None:
            return
        # before any wire traffic: local verification needs the RSA
        # primitives, and the failure should be actionable, not a
        # ModuleNotFoundError mid-request
        require_cryptography("the azure_keyvault signer")
        with self._load_lock:
            if self._pub is not None:
                return
            bundle = self.breaker.call(self._request, "GET",
                                       self._key_path())
            jwk = bundle.get("key", bundle)
            if jwk.get("kty") not in ("RSA", "RSA-HSM"):
                raise JWTError(
                    f"key vault key {self.key_name} is "
                    f"{jwk.get('kty')}, need RSA for RS256")
            n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
            e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
            from cryptography.hazmat.primitives.asymmetric.rsa import (
                RSAPublicNumbers,
            )

            # stable kid: the vault's key identifier version, else an
            # n/e digest like the local signer
            kid_src = jwk.get("kid", "")
            self._kid = (kid_src.rsplit("/", 1)[-1] if kid_src
                         else hashlib.sha256(
                             f"{n:x}:{e:x}".encode()).hexdigest()[:16])
            self._jwk = {"kty": "RSA", "use": "sig", "alg": "RS256",
                         "kid": self._kid, "n": jwk["n"],
                         "e": jwk["e"]}
            self._pub = RSAPublicNumbers(e, n).public_key()

    # -- JWTSigner surface ---------------------------------------------

    def sign(self, signing_input: bytes) -> bytes:
        self._load_public()
        digest = hashlib.sha256(signing_input).digest()
        out = self.breaker.call(
            self._request, "POST", f"{self._key_path()}/sign",
            {"alg": "RS256", "value": _b64url(digest)})
        return _b64url_decode(out["value"])

    def verify(self, signing_input: bytes, signature: bytes) -> bool:
        self._load_public()
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding

        try:
            # write-once under _load_lock, published by _load_public()
            # above (same happens-before argument as `kid`)
            # jaxlint: disable=race-unlocked-field
            self._pub.verify(signature, signing_input,
                             padding.PKCS1v15(), hashes.SHA256())
            return True
        except InvalidSignature:
            return False

    def public_jwk(self) -> dict[str, Any]:
        self._load_public()
        # write-once under _load_lock, published by _load_public()
        # jaxlint: disable=race-unlocked-field
        return dict(self._jwk)
