"""Security plane: secret providers, JWT signing/verification, OIDC.

Capability parity with the reference's ``copilot_secrets``,
``copilot_jwt_signer`` and ``copilot_auth`` adapter packages (SURVEY.md §2.1).
"""
