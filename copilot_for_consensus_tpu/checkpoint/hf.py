"""HuggingFace checkpoint import: safetensors → decoder pytree.

Replaces the reference's reliance on external engines to own the weights
(Ollama pulls GGUF blobs, ``adapters/copilot_summarization/
copilot_summarization/local_llm_summarizer.py:106-115``): here the
framework loads Mistral/Llama/Mixtral-family HF checkpoints directly into
the JAX decoder's stacked-layer pytree.

Layout notes:
* torch ``nn.Linear`` stores ``[out, in]``; our matmuls are ``x @ W`` with
  ``W: [in, out]`` — every projection transposes on load.
* per-layer tensors stack on a leading ``n_layers`` axis (the decoder
  drives layers with ``lax.scan``), so we allocate the stacked array once
  and fill it layer by layer with lazily-read tensors.
* RoPE: both sides use the rotate-half convention, so q/k need no
  permutation.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Callable

import numpy as np

from copilot_for_consensus_tpu.models.configs import DecoderConfig

try:  # numpy bf16 via ml_dtypes (ships with jax)
    import ml_dtypes

    _DTYPES = {"bfloat16": ml_dtypes.bfloat16, "float32": np.float32,
               "float16": np.float16}
except Exception:  # pragma: no cover
    _DTYPES = {"float32": np.float32, "float16": np.float16}


class CheckpointError(RuntimeError):
    pass


def read_hf_config(path: str | pathlib.Path) -> dict:
    cfg_file = pathlib.Path(path) / "config.json"
    if not cfg_file.exists():
        raise CheckpointError(f"no config.json under {path}")
    return json.loads(cfg_file.read_text())


def config_from_hf(hf: dict) -> DecoderConfig:
    """Map an HF ``config.json`` to a :class:`DecoderConfig`."""
    model_type = hf.get("model_type", "")
    if model_type not in ("mistral", "llama", "mixtral"):
        raise CheckpointError(
            f"unsupported model_type {model_type!r} (mistral/llama/mixtral)")
    d_model = hf["hidden_size"]
    n_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or d_model // n_heads
    if head_dim != d_model // n_heads:
        raise CheckpointError(
            f"head_dim {head_dim} != hidden_size/num_heads "
            f"{d_model // n_heads}: decoupled head_dim is unsupported")
    scaling = hf.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type")) not in (
            None, "default"):
        # Silently dropping e.g. llama3 rope scaling would load fine and
        # garble every long-context forward — fail loudly instead.
        raise CheckpointError(
            f"rope_scaling {scaling!r} is unsupported (plain RoPE only)")
    return DecoderConfig(
        name=hf.get("_name_or_path") or model_type,
        vocab_size=hf["vocab_size"],
        d_model=d_model,
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=hf.get("num_key_value_heads", n_heads),
        d_ff=hf["intermediate_size"],
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        max_seq_len=hf.get("max_position_embeddings", 32768),
        sliding_window=hf.get("sliding_window") or 0,
        norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        n_experts=hf.get("num_local_experts", 0),
        experts_per_token=hf.get("num_experts_per_tok", 2),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )


def _tensor_index(path: pathlib.Path) -> dict[str, pathlib.Path]:
    """tensor name → shard file, for single-file and sharded checkpoints."""
    index_file = path / "model.safetensors.index.json"
    if index_file.exists():
        index = json.loads(index_file.read_text())
        return {name: path / shard
                for name, shard in index["weight_map"].items()}
    single = path / "model.safetensors"
    if single.exists():
        from safetensors import safe_open

        with safe_open(single, framework="np") as f:
            return {name: single for name in f.keys()}
    raise CheckpointError(f"no model.safetensors[.index.json] under {path}")


class _LazyReader:
    """Reads tensors by name across shard files, one file handle per shard
    (a Mixtral load issues ~1000 tensor reads; re-opening and re-parsing
    the safetensors header per read is pure cold-start waste)."""

    def __init__(self, path: pathlib.Path):
        self.index = _tensor_index(path)
        self._handles: dict[pathlib.Path, Any] = {}

    def __contains__(self, name: str) -> bool:
        return name in self.index

    def _handle(self, shard: pathlib.Path):
        f = self._handles.get(shard)
        if f is None:
            from safetensors import safe_open

            f = self._handles[shard] = safe_open(shard, framework="np")
        return f

    def get(self, name: str) -> np.ndarray:
        shard = self.index.get(name)
        if shard is None:
            raise CheckpointError(f"tensor {name!r} missing from checkpoint")
        return self._handle(shard).get_tensor(name)


def _stacked(reader: _LazyReader, n_layers: int, dtype,
             name_for: Callable[[int], str],
             transform: Callable[[np.ndarray], np.ndarray] = lambda x: x
             ) -> np.ndarray:
    """Allocate [n_layers, ...] once, fill with per-layer reads."""
    first = transform(reader.get(name_for(0))).astype(dtype)
    out = np.empty((n_layers,) + first.shape, dtype=dtype)
    out[0] = first
    for i in range(1, n_layers):
        out[i] = transform(reader.get(name_for(i))).astype(dtype)
    return out


def load_hf_params(path: str | pathlib.Path, cfg: DecoderConfig,
                   dtype: str = "bfloat16") -> dict[str, Any]:
    """Load an HF Mistral/Llama/Mixtral checkpoint as our decoder pytree
    (numpy leaves; caller moves to device / shards / quantizes)."""
    np_dtype = _DTYPES.get(dtype)
    if np_dtype is None:
        raise CheckpointError(f"unsupported dtype {dtype!r}")
    reader = _LazyReader(pathlib.Path(path))
    n = cfg.n_layers
    T = np.ascontiguousarray

    def t(w: np.ndarray) -> np.ndarray:       # torch [out,in] → [in,out]
        return T(w.T)

    def lname(stem: str) -> Callable[[int], str]:
        return lambda i: f"model.layers.{i}.{stem}.weight"

    layer: dict[str, Any] = {
        "attn_norm": _stacked(reader, n, np_dtype,
                              lname("input_layernorm")),
        "wq": _stacked(reader, n, np_dtype, lname("self_attn.q_proj"), t),
        "wk": _stacked(reader, n, np_dtype, lname("self_attn.k_proj"), t),
        "wv": _stacked(reader, n, np_dtype, lname("self_attn.v_proj"), t),
        "wo": _stacked(reader, n, np_dtype, lname("self_attn.o_proj"), t),
        "ffn_norm": _stacked(reader, n, np_dtype,
                             lname("post_attention_layernorm")),
    }
    if cfg.is_moe:
        e = cfg.n_experts

        def expert_stack(w_name: str) -> np.ndarray:
            # [n_layers, n_experts, in, out]
            first = t(reader.get(
                f"model.layers.0.block_sparse_moe.experts.0.{w_name}.weight"))
            out = np.empty((n, e) + first.shape, dtype=np_dtype)
            for i in range(n):
                for j in range(e):
                    out[i, j] = t(reader.get(
                        f"model.layers.{i}.block_sparse_moe."
                        f"experts.{j}.{w_name}.weight")).astype(np_dtype)
            return out

        layer.update({
            # router stays fp32: routing decisions are precision-sensitive
            "router": _stacked(reader, n, np.float32,
                               lname("block_sparse_moe.gate"), t),
            "w_gate": expert_stack("w1"),
            "w_up": expert_stack("w3"),
            "w_down": expert_stack("w2"),
        })
    else:
        layer.update({
            "w_gate": _stacked(reader, n, np_dtype, lname("mlp.gate_proj"),
                               t),
            "w_up": _stacked(reader, n, np_dtype, lname("mlp.up_proj"), t),
            "w_down": _stacked(reader, n, np_dtype, lname("mlp.down_proj"),
                               t),
        })

    params: dict[str, Any] = {
        "tok_emb": reader.get("model.embed_tokens.weight").astype(np_dtype),
        "layers": layer,
        "final_norm": reader.get("model.norm.weight").astype(np_dtype),
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in reader:
            params["lm_head"] = t(
                reader.get("lm_head.weight")).astype(np_dtype)
        else:
            raise CheckpointError(
                "config says untied embeddings but lm_head.weight is "
                "missing from the checkpoint")
    return params


def load_hf_checkpoint(path: str | pathlib.Path, dtype: str = "bfloat16"
                       ) -> tuple[DecoderConfig, dict[str, Any]]:
    cfg = config_from_hf(read_hf_config(path))
    return cfg, load_hf_params(path, cfg, dtype)


# ---------------------------------------------------------------------------
# Encoder (BERT/MiniLM family) import — the weights behind the reference's
# default embedder all-MiniLM-L6-v2 (``adapters/copilot_embedding/
# copilot_embedding/sentence_transformer_provider.py:19-51``); loading
# them first-party replaces the sentence-transformers dependency.
# ---------------------------------------------------------------------------


def encoder_config_from_hf(hf: dict) -> "EncoderConfig":
    from copilot_for_consensus_tpu.models.configs import EncoderConfig

    if hf.get("model_type") != "bert":
        raise CheckpointError(
            f"unsupported encoder model_type {hf.get('model_type')!r} "
            "(bert family only)")
    act = hf.get("hidden_act", "gelu")
    if act != "gelu":
        raise CheckpointError(f"unsupported hidden_act {act!r}")
    pos_type = hf.get("position_embedding_type", "absolute")
    if pos_type != "absolute":
        # Loading a relative-position BERT as absolute would serve
        # silently-wrong vectors; fail loudly like the decoder loader
        # does for rope_scaling.
        raise CheckpointError(
            f"unsupported position_embedding_type {pos_type!r}")
    return EncoderConfig(
        name=hf.get("_name_or_path") or "bert",
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        d_ff=hf["intermediate_size"],
        max_positions=hf.get("max_position_embeddings", 512),
        norm_eps=float(hf.get("layer_norm_eps", 1e-12)),
    )


def load_hf_encoder_params(path: str | pathlib.Path, cfg: "EncoderConfig",
                           dtype: str = "float32") -> dict[str, Any]:
    """BERT-family safetensors → encoder pytree. Single-segment serving:
    the type-0 segment embedding is a constant addend at every position,
    so it folds into ``pos_emb`` and token_type_ids disappear."""
    np_dtype = _DTYPES.get(dtype)
    if np_dtype is None:
        raise CheckpointError(f"unsupported dtype {dtype!r}")
    reader = _LazyReader(pathlib.Path(path))
    # BertModel saves bare names; BertFor* task models prefix "bert.".
    prefix = "" if "embeddings.word_embeddings.weight" in reader else "bert."
    if f"{prefix}embeddings.word_embeddings.weight" not in reader:
        raise CheckpointError("no BERT embedding tensors in checkpoint")

    def g(name: str) -> np.ndarray:
        return reader.get(prefix + name)

    n = cfg.n_layers
    T = np.ascontiguousarray

    def t(w: np.ndarray) -> np.ndarray:       # torch [out,in] → [in,out]
        return T(w.T)

    def lname(stem: str, leaf: str = "weight") -> Callable[[int], str]:
        return lambda i: f"{prefix}encoder.layer.{i}.{stem}.{leaf}"

    def stack(stem: str, leaf: str = "weight",
              transform: Callable[[np.ndarray], np.ndarray] = lambda x: x
              ) -> np.ndarray:
        return _stacked(reader, n, np_dtype, lname(stem, leaf), transform)

    pos = g("embeddings.position_embeddings.weight").astype(np.float32)
    pos = pos + g("embeddings.token_type_embeddings.weight")[0].astype(
        np.float32)
    return {
        "tok_emb": g("embeddings.word_embeddings.weight").astype(np_dtype),
        "pos_emb": pos.astype(np_dtype),
        "emb_norm_w": g("embeddings.LayerNorm.weight").astype(np_dtype),
        "emb_norm_b": g("embeddings.LayerNorm.bias").astype(np_dtype),
        "layers": {
            "wq": stack("attention.self.query", transform=t),
            "wk": stack("attention.self.key", transform=t),
            "wv": stack("attention.self.value", transform=t),
            "wo": stack("attention.output.dense", transform=t),
            "wq_b": stack("attention.self.query", "bias"),
            "wk_b": stack("attention.self.key", "bias"),
            "wv_b": stack("attention.self.value", "bias"),
            "wo_b": stack("attention.output.dense", "bias"),
            "attn_norm_w": stack("attention.output.LayerNorm"),
            "attn_norm_b": stack("attention.output.LayerNorm", "bias"),
            "w_in": stack("intermediate.dense", transform=t),
            "b_in": stack("intermediate.dense", "bias"),
            "w_out": stack("output.dense", transform=t),
            "b_out": stack("output.dense", "bias"),
            "ffn_norm_w": stack("output.LayerNorm"),
            "ffn_norm_b": stack("output.LayerNorm", "bias"),
        },
    }


def load_hf_encoder_checkpoint(path: str | pathlib.Path,
                               dtype: str = "float32"
                               ) -> tuple["EncoderConfig", dict[str, Any]]:
    cfg = encoder_config_from_hf(read_hf_config(path))
    return cfg, load_hf_encoder_params(path, cfg, dtype)
