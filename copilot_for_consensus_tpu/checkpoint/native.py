"""Native serving checkpoints: mmap-fast safetensors + config metadata.

The serving-side half of the checkpoint story (reference counterpart:
Ollama's model blob cache — external; here first-party). A native
checkpoint directory holds:

* ``model.safetensors`` — the decoder pytree flattened with ``/``-joined
  keys. Int8-quantized leaves appear naturally as ``<path>/q`` +
  ``<path>/scale`` (the in-memory representation is already a dict).
* ``meta.json`` — DecoderConfig fields + format marker + tokenizer info.
* ``tokenizer.json`` — optional; copied from the source HF checkpoint so
  serving needs exactly one directory.

Quantization happens offline on the host (numpy) where RAM is plentiful,
so a 7B never needs bf16+int8 copies in HBM at once — load time becomes
an mmap read instead of a device-side quantization pass.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import shutil

import numpy as np

from copilot_for_consensus_tpu.checkpoint.hf import (
    CheckpointError,
    _DTYPES,
    load_hf_checkpoint,
)
from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.models.quant import DECODER_QUANT_LEAVES

FORMAT = "copilot-tpu-native-v1"


def _flatten(tree: dict, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _quantize_np(w: np.ndarray) -> dict[str, np.ndarray]:
    """Host-side mirror of ``models.quant.quantize_tensor`` (numpy)."""
    wf = w.astype(np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale}


def _quantize_np_int4(w: np.ndarray, group: int = 256
                      ) -> dict[str, np.ndarray]:
    """Host-side mirror of ``models.quant.quantize_tensor_int4``:
    group-wise signed nibbles packed two per int8 byte along the
    contraction axis (layout: ``ops.quant_matmul.pack_int4``)."""
    *lead, d, f = w.shape
    group = min(group, d)          # small models: one group spans D
    if d % group:
        raise ValueError(f"contraction dim {d} not divisible by "
                         f"group {group}")
    wf = w.astype(np.float32).reshape(*lead, d // group, group, f)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / 7.0, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale), -8, 7).astype(np.int32)
    q = q.reshape(*lead, d, f)
    lo = q[..., 0::2, :] & 0xF
    hi = q[..., 1::2, :] & 0xF
    return {"q4": ((hi << 4) | lo).astype(np.int8),
            "scale": scale.reshape(*lead, d // group, f)}


def quantize_tree(params: dict,
                  leaves: tuple[tuple[str, ...], ...] = DECODER_QUANT_LEAVES,
                  mode: str = "int8") -> dict:
    """Quantize the given leaves of a numpy pytree (int8 per-channel or
    int4 group-wise packed), in place per leaf."""
    out = {k: (quantize_tree(v, tuple(
        rest[1:] for rest in leaves if rest and rest[0] == k), mode)
        if isinstance(v, dict) else v) for k, v in params.items()}
    for path in leaves:
        if len(path) == 1 and path[0] in params and not isinstance(
                params[path[0]], dict):
            w = np.asarray(params[path[0]])
            out[path[0]] = (_quantize_np(w) if mode == "int8"
                            else _quantize_np_int4(w))
    return out


def _norm_token_id(value, default: int) -> tuple[int, list[int]]:
    """HF configs may carry an int or a list (Llama-3.1 multi-EOS).
    Returns (primary, all)."""
    if isinstance(value, (list, tuple)) and value:
        ids = [int(v) for v in value]
        return ids[0], ids
    if value is None:
        return default, [default]
    return int(value), [int(value)]


def save_native(path: str | pathlib.Path, cfg: DecoderConfig, params: dict,
                *, tokenizer_file: str | pathlib.Path | None = None,
                bos_id=None, eos_id=None) -> None:
    from safetensors.numpy import save_file

    out = pathlib.Path(path)
    out.mkdir(parents=True, exist_ok=True)
    flat = _flatten(params)
    save_file(flat, out / "model.safetensors")
    bos, _ = _norm_token_id(bos_id, 1)
    eos, eos_ids = _norm_token_id(eos_id, 2)
    meta = {
        "format": FORMAT,
        "config": dataclasses.asdict(cfg),
        # "int8" / "int4" / False — engines pass this straight through
        # as the quantize mode (older checkpoints stored a bool; True
        # meant int8 and still does).
        "quantized": ("int4" if any(k.endswith("/q4") for k in flat)
                      else "int8" if any(k.endswith("/q") for k in flat)
                      else False),
        "bos_id": bos,
        "eos_id": eos,
        "eos_ids": eos_ids,
    }
    (out / "meta.json").write_text(json.dumps(meta, indent=2))
    if tokenizer_file is not None and pathlib.Path(tokenizer_file).exists():
        shutil.copy(tokenizer_file, out / "tokenizer.json")


def is_native(path: str | pathlib.Path) -> bool:
    meta = pathlib.Path(path) / "meta.json"
    if not meta.exists():
        return False
    try:
        return json.loads(meta.read_text()).get("format") == FORMAT
    except (json.JSONDecodeError, OSError):
        return False


def load_native(path: str | pathlib.Path
                ) -> tuple[DecoderConfig, dict, dict]:
    """Returns (cfg, params, meta). Leaves are numpy (zero-copy where the
    safetensors mmap allows); caller device-puts / shards."""
    from safetensors.numpy import load_file

    p = pathlib.Path(path)
    meta = json.loads((p / "meta.json").read_text())
    if meta.get("format") != FORMAT:
        raise CheckpointError(f"{path} is not a {FORMAT} checkpoint")
    cfg = DecoderConfig(**meta["config"])
    params = _unflatten(load_file(p / "model.safetensors"))
    return cfg, params, meta


def load_checkpoint(path: str | pathlib.Path, dtype: str = "bfloat16"
                    ) -> tuple[DecoderConfig, dict, dict]:
    """Auto-detect: native dir → as saved; HF dir → converted in memory.

    Returns (cfg, params, meta) with numpy leaves.
    """
    p = pathlib.Path(path)
    if is_native(p):
        return load_native(p)
    cfg, params = load_hf_checkpoint(p, dtype)
    hf_cfg = json.loads((p / "config.json").read_text())
    bos, _ = _norm_token_id(hf_cfg.get("bos_token_id"), 1)
    eos, eos_ids = _norm_token_id(hf_cfg.get("eos_token_id"), 2)
    meta = {
        "format": "hf", "quantized": False,
        "bos_id": bos, "eos_id": eos, "eos_ids": eos_ids,
    }
    return cfg, params, meta


def convert(src: str | pathlib.Path, dst: str | pathlib.Path, *,
            quantize: bool | str = True, dtype: str = "bfloat16") -> dict:
    """Offline converter: HF checkpoint → native serving checkpoint.

    The role of ``ollama pull`` + GGUF quantization in the reference
    stack, first-party. ``quantize``: False | True/"int8" | "int4".
    Returns the written meta dict.
    """
    src, dst = pathlib.Path(src), pathlib.Path(dst)
    cfg, params = load_hf_checkpoint(src, dtype)
    if quantize:
        params = quantize_tree(
            params, mode="int8" if quantize is True else quantize)
    hf_cfg = json.loads((src / "config.json").read_text())
    # Raw values straight through — save_native's _norm_token_id handles
    # None and list forms; coalescing here would corrupt a real id 0.
    save_native(
        dst, cfg, params,
        tokenizer_file=src / "tokenizer.json",
        bos_id=hf_cfg.get("bos_token_id"),
        eos_id=hf_cfg.get("eos_token_id"))
    return json.loads((dst / "meta.json").read_text())


def load_tokenizer(path: str | pathlib.Path):
    """HFTokenizer from a checkpoint dir's ``tokenizer.json``, with
    bos/eos ids taken from the checkpoint metadata. None if absent."""
    from copilot_for_consensus_tpu.engine.tokenizer import HFTokenizer

    p = pathlib.Path(path)
    tok_file = p / "tokenizer.json"
    if not tok_file.exists():
        return None
    bos, eos = 1, [2]
    meta_file = p / "meta.json"
    cfg_file = p / "config.json"
    if meta_file.exists():
        meta = json.loads(meta_file.read_text())
        bos = meta.get("bos_id", 1)
        eos = meta.get("eos_ids") or [meta.get("eos_id", 2)]
    elif cfg_file.exists():
        hf = json.loads(cfg_file.read_text())
        bos, _ = _norm_token_id(hf.get("bos_token_id"), 1)
        _, eos = _norm_token_id(hf.get("eos_token_id"), 2)
    return HFTokenizer(str(tok_file), bos_id=bos, eos_id=eos)


__all__ = [
    "CheckpointError", "FORMAT", "convert", "is_native", "load_checkpoint",
    "load_native", "load_tokenizer", "quantize_tree", "save_native",
    "_DTYPES",
]
