"""Checkpoint subsystem: HF import, native quantized serving format.

Fills the model-weights role the reference delegates to Ollama's blob
store and HF hub downloads (``local_llm_summarizer.py``,
``sentence_transformer_provider.py``) — first-party, mmap-fast, with
offline int8 quantization for serving.
"""

from copilot_for_consensus_tpu.checkpoint.hf import (
    CheckpointError,
    config_from_hf,
    encoder_config_from_hf,
    load_hf_checkpoint,
    load_hf_encoder_checkpoint,
    load_hf_encoder_params,
    load_hf_params,
    read_hf_config,
)
from copilot_for_consensus_tpu.checkpoint.native import (
    FORMAT,
    convert,
    is_native,
    load_checkpoint,
    load_native,
    load_tokenizer,
    quantize_tree,
    save_native,
)
from copilot_for_consensus_tpu.checkpoint.train_state import (
    TrainCheckpointer,
)

__all__ = [
    "CheckpointError", "FORMAT", "TrainCheckpointer", "config_from_hf",
    "convert", "encoder_config_from_hf", "is_native", "load_checkpoint",
    "load_hf_checkpoint", "load_hf_encoder_checkpoint",
    "load_hf_encoder_params", "load_hf_params", "load_native",
    "load_tokenizer", "quantize_tree", "read_hf_config", "save_native",
]
