"""Training-state checkpointing: Orbax save/restore of (step, params,
opt_state) with retention.

SURVEY §5 ("Checkpoint / resume") assigns the TPU build Orbax
checkpoints for model state plus slice-level preemption checkpointing
for long batch jobs — the role MongoDB's durable doc-status state
machine plays for the *pipeline*, applied to the *training loop*
(``train.py``). A preempted fine-tuning job resumes from the last kept
step with bit-identical state: params, optimizer moments, and the step
counter all round-trip.

Sharded pytrees work transparently: Orbax records and restores each
array's sharding, so a ``pjit``-trained state saved from an N-device
mesh restores onto the same mesh layout without gathering to one host.
"""

from __future__ import annotations

import pathlib
from typing import Any

import jax


class TrainCheckpointer:
    """Step-numbered checkpoints with retention, atomic finalization,
    and latest-step resume."""

    def __init__(self, directory: str | pathlib.Path,
                 max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = pathlib.Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    # ------------------------------------------------------------------

    def save(self, step: int, params: Any, opt_state: Any,
             force: bool = False) -> bool:
        """Persist one training state. Returns False if the manager's
        save policy skipped it (never skips with default options)."""
        import orbax.checkpoint as ocp

        saved = self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
            force=force,
        )
        # Block until the async write is durable: a preemption right
        # after save() returning must not lose the step.
        self._mgr.wait_until_finished()
        return saved

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, step: int | None = None,
                like: tuple[Any, Any] | None = None
                ) -> tuple[int, Any, Any]:
        """Restore (step, params, opt_state). ``like`` provides abstract
        target trees (e.g. from ``jax.eval_shape`` or a freshly-built
        state) so arrays restore with the right dtype/sharding."""
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        if like is not None:
            p_like = jax.tree.map(ocp.utils.to_shape_dtype_struct, like[0])
            o_like = jax.tree.map(ocp.utils.to_shape_dtype_struct, like[1])
            args = ocp.args.Composite(
                params=ocp.args.StandardRestore(p_like),
                opt_state=ocp.args.StandardRestore(o_like),
            )
        else:
            args = ocp.args.Composite(
                params=ocp.args.StandardRestore(),
                opt_state=ocp.args.StandardRestore(),
            )
        out = self._mgr.restore(step, args=args)
        return step, out["params"], out["opt_state"]

    def all_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
