"""On-device (TPU) vector store: exact flat search + sharded IVF ANN.

The role FAISS/Qdrant play for the reference
(``adapters/copilot_vectorstore/faiss_store.py:18,101-105``,
``qdrant_store.py:78``), redesigned for the chip: vectors live as one
HBM-resident [capacity, dim] matrix; the default ``index="flat"`` route
scores a query as a single fused ``scores = M @ q`` matvec plus
``lax.top_k`` on the MXU/VPU — exact cosine search at HBM bandwidth, no
index build, no recall loss. 10M 384-dim bf16 vectors ≈ 7.4 GB: a v5e
chip holds the whole corpus.

``index="ivf"`` layers a two-tier IVF index (vectorstore/ivf.py) over
the SAME matrix for the million-chunk regime where O(corpus) per query
becomes the wall: a k-means coarse quantizer routes each query to
``nprobe`` posting lists of global row ids, candidates are gathered and
exactly rescored against the live matrix, and posting lists shard over
a dp-only mesh (``mesh="auto"``) with a host cross-shard top-k merge.
Flat stays the recall oracle; the IVF route is gated at recall@10 ≥
0.95 on the bench preset. Freshly-ingested rows append to a spill
block scored on every query, so ``add_embeddings`` never blocks on a
rebuild; the quantizer retrains lazily on the query path when spill
drift or corpus growth crosses the IVFParams thresholds.

Filtered queries (``thread_id=...``) use a host-side inverted index over
metadata: highly selective filters score just the candidate rows on
host; broad filters run the device path with top-k oversampling (the
IVF route falls back to exact flat for under-filled filtered queries,
keeping filtered results no worse than the oracle). Capacity grows
geometrically; the device buffer is rebuilt on growth and patched in
place (jitted dynamic_update_slice) for small flushes.

Retrieval is a first-class observable stage: ``set_metrics`` wires a
collector and every query records ``vectorstore_query_seconds`` /
``vectorstore_queries_total`` (per route) plus nprobe / lists_scanned /
spill-fraction series on the IVF route, and ``last_query_stats`` feeds
the orchestrator's retrieval trace span.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Mapping, Sequence

import numpy as np

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    HloSpec,
    checkable,
)
from copilot_for_consensus_tpu.obs.metrics import check_registry_labels
from copilot_for_consensus_tpu.storage.base import matches_filter
from copilot_for_consensus_tpu.vectorstore._inverted import InvertedIndexMixin
from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)
from copilot_for_consensus_tpu.vectorstore.ivf import (
    IVFIndex,
    IVFParams,
    next_pow2,
)

_SELECTIVE_HOST_LIMIT = 4096     # filter hits below this → host-side scoring

#: retrieval telemetry families the store emits through
#: ``set_metrics`` (exposition-prefixed names) — the registry-next-to-
#: emitter discipline (PR 5): dashboards and alert exprs can only
#: reference series the code actually emits
#: (tests/test_observability_pack.py).
VECTORSTORE_METRICS = {
    "copilot_vectorstore_query_seconds": (
        "histogram", ("route",),
        "end-to-end query_batch latency per index route"),
    "copilot_vectorstore_queries_total": (
        "counter", ("route",),
        "queries answered per index route (flat | ivf | host)"),
    "copilot_vectorstore_query_nprobe": (
        "gauge", (),
        "posting lists probed per query on the ivf route"),
    "copilot_vectorstore_lists_scanned_total": (
        "counter", (),
        "posting lists scanned, summed over queries (ivf route)"),
    "copilot_vectorstore_spill_fraction": (
        "gauge", (),
        "fraction of live vectors answered from the spill block — "
        "sustained > ivf_spill_fraction means retrain is lagging"),
    "copilot_vectorstore_retrains_total": (
        "counter", (),
        "coarse-quantizer (re)trains — drift policy firings"),
}

# proc/role are stamped by the cross-process aggregator (obs/ship.py);
# declaring them here must fail at import, not at scrape time.
check_registry_labels(VECTORSTORE_METRICS, owner="VECTORSTORE_METRICS")

# hlo-peak-memory budgets for the IVF search dispatch at the contract
# factories' tiny shapes (~2× the measured compiled peak — they gate
# structural working-set blowups, not byte drift; see HloSpec).
_IVF_SEARCH_PEAK_BUDGET = 48 * 1024        # measured 23,008 B
_IVF_SEARCH_MESH_PEAK_BUDGET = 64 * 1024   # measured 34,080 B


class TPUVectorStore(InvertedIndexMixin, VectorStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self._dim: int | None = cfg.get("dimension") or None
        self._dtype_name = cfg.get("dtype", "bfloat16")
        self.persist_path = cfg.get("persist_path")
        self._index_kind = cfg.get("index", "flat")
        if self._index_kind not in ("flat", "ivf"):
            raise VectorStoreError(
                f"unknown index {self._index_kind!r} (flat|ivf)")
        self._ivf_params = IVFParams.from_config(cfg)
        self._mesh_cfg = cfg.get("mesh", "none")
        self._mesh = None
        self._mesh_built = False
        self._ivf: IVFIndex | None = None
        self.metrics = None                          # set via set_metrics
        self.last_query_stats: dict[str, Any] | None = None
        self._lock = threading.RLock()
        self._ids: list[str] = []
        self._index: dict[str, int] = {}
        self._metadata: list[dict[str, Any]] = []
        self._host: np.ndarray | None = None        # [n, dim] fp32 master
        self._init_inverted()
        self._device = None                          # [capacity, dim]
        self._device_rows = 0                        # rows synced
        self._deleted_rows: set[int] = set()
        self._batch_query_fn = None
        self._patch_fn = None
        self._zero_fn = None

    # -- lazy jax ------------------------------------------------------

    def _jax(self):
        import jax
        import jax.numpy as jnp
        return jax, jnp

    def _get_mesh(self):
        """dp-only retrieval mesh when configured; built lazily so a
        flat store never touches the device topology."""
        if self._mesh_built:
            return self._mesh
        self._mesh_built = True
        if self._mesh_cfg in (None, "none", "", 0, False):
            return None
        import jax

        from copilot_for_consensus_tpu.parallel.mesh import retrieval_mesh
        if self._mesh_cfg == "auto":
            n = len(jax.devices())
            self._mesh = retrieval_mesh(n) if n > 1 else None
        else:
            self._mesh = retrieval_mesh(int(self._mesh_cfg))
        return self._mesh

    def set_metrics(self, collector) -> None:
        """Wire a MetricsCollector; queries then emit the
        ``vectorstore_*`` series (obs/metrics.py namespace-prefixes)."""
        with self._lock:
            self.metrics = collector

    @property
    def dimension(self) -> int | None:
        with self._lock:
            return self._dim

    def count(self) -> int:
        with self._lock:
            return len(self._ids) - len(self._deleted_rows)

    # -- writes --------------------------------------------------------

    def add_embedding(self, vec_id, vector, metadata=None):
        self.add_embeddings([(vec_id, vector, metadata)])

    def add_embeddings(self, items) -> int:
        jaxmod, jnp = self._jax()
        n = 0
        with self._lock:
            rows, vecs = [], []
            for vec_id, vector, metadata in items:
                arr = np.asarray(vector, dtype=np.float32)
                if self._dim is None:
                    self._dim = int(arr.shape[0])
                if arr.shape[0] != self._dim:
                    raise VectorStoreError(
                        f"dimension mismatch: {arr.shape[0]} != {self._dim}")
                norm = float(np.linalg.norm(arr))
                if norm > 0:
                    arr = arr / norm
                meta = dict(metadata or {})
                if vec_id in self._index:            # upsert semantics
                    row = self._index[vec_id]
                    self._unindex_meta(row)
                    self._host[row] = arr
                    self._metadata[row] = meta
                    self._index_meta(row, meta)
                    self._deleted_rows.discard(row)
                    rows.append(row)
                    vecs.append(arr)
                else:
                    row = len(self._ids)
                    self._ids.append(vec_id)
                    self._index[vec_id] = row
                    self._metadata.append(meta)
                    self._index_meta(row, meta)
                    self._append_host(arr)
                    rows.append(row)
                    vecs.append(arr)
                n += 1
            self._sync_device(rows, vecs)
            if self._ivf is not None and self._ivf.trained and rows:
                # upserted rows move list→spill (their centroid may no
                # longer be nearest); new rows append to spill. Either
                # way the next query sees them — the rescore reads the
                # live matrix, the spill is scored exactly.
                self._ivf.remove(rows)
                self._ivf.add(rows)
        return n

    def _append_host(self, arr: np.ndarray) -> None:
        if self._host is None:
            self._host = np.zeros((16, self._dim), dtype=np.float32)
        if len(self._ids) > self._host.shape[0]:
            grown = np.zeros((self._host.shape[0] * 2, self._dim),
                             dtype=np.float32)
            grown[:self._host.shape[0]] = self._host
            self._host = grown
        self._host[len(self._ids) - 1] = arr

    def _unindex_meta(self, row: int) -> None:
        meta = self._metadata[row]
        for k, v in meta.items():
            if isinstance(v, (str, int, bool)):
                self._inverted[(k, v)].discard(row)

    def _sync_device(self, rows: list[int], vecs: list[np.ndarray]) -> None:
        """Patch the device buffer; rebuild on growth."""
        jaxmod, jnp = self._jax()
        dtype = getattr(jnp, self._dtype_name)
        capacity = self._host.shape[0] if self._host is not None else 0
        if (self._device is None
                or self._device.shape[0] != capacity):
            arr = self._host.astype(np.float32)
            mesh = (self._get_mesh() if self._index_kind == "ivf"
                    else None)
            if mesh is not None:
                # replicate over the retrieval mesh so the sharded IVF
                # dispatch gathers candidates without a reshard copy
                from jax.sharding import NamedSharding, PartitionSpec
                self._device = jaxmod.device_put(
                    arr, NamedSharding(mesh, PartitionSpec(None, None))
                ).astype(dtype)
            else:
                self._device = jaxmod.device_put(arr).astype(dtype)
            self._device_rows = len(self._ids)
            return
        if not rows:
            return
        if self._patch_fn is None:
            def patch(buf, updates, starts):
                def one(buf, pair):
                    vec, start = pair
                    return jaxmod.lax.dynamic_update_slice(
                        buf, vec.astype(buf.dtype)[None, :],
                        (start, 0)), None
                buf, _ = jaxmod.lax.scan(one, buf, (updates, starts))
                return buf
            self._patch_fn = jaxmod.jit(patch, donate_argnums=(0,))
        self._device = self._patch_fn(
            self._device, jnp.asarray(np.stack(vecs), dtype=jnp.float32),
            jnp.asarray(rows, dtype=jnp.int32))
        self._device_rows = len(self._ids)

    # -- IVF maintenance ----------------------------------------------

    def _ensure_ivf(self) -> IVFIndex:
        if self._ivf is None:
            self._ivf = IVFIndex(self._dim, self._ivf_params,
                                 mesh=self._get_mesh())
        return self._ivf

    def _maybe_retrain(self) -> None:
        """Lazy (re)train on the query path — never on ingest. First
        train once the corpus reaches min_train; retrain when spill
        drift or corpus growth crosses the IVFParams thresholds."""
        if self._index_kind != "ivf" or self._host is None:
            return
        live = len(self._ids) - len(self._deleted_rows)
        ivf = self._ensure_ivf()
        if not ivf.needs_retrain(live):
            return
        rows = [i for i in range(len(self._ids))
                if i not in self._deleted_rows]
        ivf.rebuild(self._host, rows)
        if self.metrics is not None:
            self.metrics.increment("vectorstore_retrains_total")

    # -- reads ---------------------------------------------------------

    def get(self, vec_id):
        with self._lock:
            row = self._index.get(vec_id)
            if row is None or row in self._deleted_rows:
                return None
            return self._host[row].tolist(), dict(self._metadata[row])

    def query(self, vector, top_k: int = 10, flt=None):
        return self.query_batch([vector], top_k=top_k, flt=flt)[0]

    def query_batch(self, vectors, top_k: int = 10, flt=None):
        """B queries in ONE device dispatch: [B, D] @ HBM matrixᵀ with a
        per-row top-k (flat), or the fused IVF probe→gather→rescore
        dispatch when the index is trained. Single queries over the
        tunnel are round-trip latency-bound (~5 QPS measured at
        100k×384); batching moves the store to compute-bound territory
        (~1000 QPS at batch 256)."""
        with self._lock:
            n = len(self._ids)
            if n == 0 or self._dim is None:
                return [[] for _ in vectors]
            t0 = time.perf_counter()
            qs = np.asarray(list(vectors), dtype=np.float32)
            norms = np.linalg.norm(qs, axis=1, keepdims=True)
            qs = np.where(norms > 0, qs / np.maximum(norms, 1e-30), qs)
            self._maybe_retrain()
            if flt:
                cand = self._filter_rows(flt)
                if cand is not None and len(cand) <= _SELECTIVE_HOST_LIMIT:
                    out = [self._host_query(q, cand, top_k, flt)
                           for q in qs]
                    self._record_query("host", len(qs), t0)
                    return out
            if (self._index_kind == "ivf" and self._ivf is not None
                    and self._ivf.trained):
                out, stats, esc = self._ivf_query_many(qs, top_k, flt)
                self._record_query("ivf", len(qs), t0, stats, esc)
                return out
            out = self._device_query_many(qs, top_k, flt)
            self._record_query("flat", len(qs), t0)
            return out

    def _record_query(self, route: str, nq: int, t0: float,
                      stats: dict | None = None,
                      escalations: int = 0) -> None:
        dur = time.perf_counter() - t0
        snap: dict[str, Any] = {
            "route": route, "queries": nq, "duration_s": dur,
            "escalations": escalations,
        }
        if stats:
            snap.update(
                nprobe=stats["nprobe"],
                lists_scanned=stats["lists_scanned"],
                lists_scanned_frac=stats["lists_scanned_frac"],
                spill_fraction=stats["spill_fraction"])
        self.last_query_stats = snap
        m = self.metrics
        if m is None:
            return
        m.observe("vectorstore_query_seconds", dur,
                  labels={"route": route})
        m.increment("vectorstore_queries_total", float(nq),
                    labels={"route": route})
        if stats:
            m.gauge("vectorstore_query_nprobe", float(stats["nprobe"]))
            m.increment("vectorstore_lists_scanned_total",
                        float(stats["lists_scanned"] * nq))
            m.gauge("vectorstore_spill_fraction",
                    float(stats["spill_fraction"]))

    def _filter_rows(self, flt: Mapping[str, Any]) -> list[int] | None:
        """Candidate rows via the shared inverted index (superset guess;
        callers re-verify with matches_filter); None = not decidable."""
        cand = self._filter_candidates(flt)
        if cand is None:
            return None
        return sorted(cand - self._deleted_rows)

    def _host_query(self, q, rows: list[int], top_k: int, flt):
        if not rows:
            return []
        sub = self._host[rows]                       # [m, dim]
        scores = sub @ q
        order = np.argsort(-scores)[:top_k]
        return [
            QueryResult(self._ids[rows[i]], float(scores[i]),
                        dict(self._metadata[rows[i]]))
            for i in order
            if matches_filter(self._metadata[rows[i]], flt)
        ]

    def _device_query(self, q, top_k: int, flt):
        return self._device_query_many(np.asarray(q, np.float32)[None],
                                       top_k, flt)[0]

    def _collect_hits(self, vals, rows, top_k, flt):
        """Host side of a device top-k: skip padding/deleted rows,
        re-verify the filter, stop at top_k. Re-enters the store RLock
        (callers already hold it) so the row-table reads are guarded."""
        out = []
        with self._lock:
            for score, row in zip(vals, rows):
                row = int(row)
                if (row < 0 or row >= len(self._ids)
                        or row in self._deleted_rows):
                    continue  # padding rows; skip
                meta = self._metadata[row]
                if flt and not matches_filter(meta, flt):
                    continue
                out.append(QueryResult(self._ids[row], float(score),
                                       dict(meta)))
                if len(out) == top_k:
                    break
        return out

    def _device_query_many(self, qs: np.ndarray, top_k: int, flt
                           ) -> list[list[QueryResult]]:
        """ONE implementation for single and batched exact device
        search: fused [B, D] @ matrixᵀ + per-row top-k, with top-k
        oversampling escalation for filtered/deleted rows. Escalation
        rounds rescore only the still-pending queries, and stop once k
        covers every live-or-dead row ever added (``len(self._ids)`` —
        deletes keep their id slot, so that IS the row count). The
        requested width rounds UP to a power of two so the escalation
        ladder compiles a bounded set of programs (k is a static arg;
        the hlo-program-cache contract pins this)."""
        jaxmod, jnp = self._jax()
        if self._batch_query_fn is None:
            def run(matrix, qv, k):
                scores = (qv.astype(matrix.dtype)
                          @ matrix.T).astype(jnp.float32)
                return jaxmod.lax.top_k(scores, k)       # [B, k] each
            self._batch_query_fn = jaxmod.jit(run, static_argnames=("k",))

        # Callers hold the store RLock; re-enter so the device-matrix
        # and row-table reads are lexically guarded.
        with self._lock:
            capacity = self._device.shape[0]
            oversample = max(top_k, 16)
            pending = list(range(len(qs)))
            results: dict[int, list[QueryResult]] = {}
            while True:
                k = min(capacity, next_pow2(oversample))
                vals, idx = self._batch_query_fn(
                    self._device, jnp.asarray(qs[pending]), k)
                vals = np.asarray(vals)
                idx = np.asarray(idx)
                still = []
                for bi, qi in enumerate(pending):
                    out = self._collect_hits(vals[bi], idx[bi],
                                             top_k, flt)
                    results[qi] = out
                    if (len(out) < top_k and k < capacity
                            and k < len(self._ids)):
                        still.append(qi)
                if not still:
                    return [results[i] for i in range(len(qs))]
                pending = still
                oversample = k * 4

    def _ivf_query_many(self, qs: np.ndarray, top_k: int, flt):
        """The ANN route: fused probe→gather→rescore dispatch (per
        shard), host cross-shard merge, then the same host-side
        verify/escalate discipline as the flat route — k escalates in
        power-of-two buckets up to everything the probed lists + spill
        can reach. Filtered queries that stay under-filled at the
        ceiling fall back to the exact route, so a filter never
        returns worse-than-oracle results."""
        ivf = self._ivf
        ceiling = max(1, ivf.max_candidates() // ivf.num_shards)
        oversample = max(top_k, 16)
        pending = list(range(len(qs)))
        results: dict[int, list[QueryResult]] = {}
        stats: dict[str, Any] = {}
        escalations = 0
        while True:
            k = min(ceiling, next_pow2(oversample))
            vals, rows, stats = ivf.search(self._device, qs[pending], k)
            still = []
            for bi, qi in enumerate(pending):
                out = self._collect_hits(vals[bi], rows[bi], top_k, flt)
                results[qi] = out
                if len(out) < top_k and k < ceiling:
                    still.append(qi)
            if not still:
                break
            pending = still
            oversample = k * 4
            escalations += 1
        if flt:
            short = [i for i in range(len(qs))
                     if len(results[i]) < top_k]
            if short:
                for i, exact in zip(
                        short,
                        self._device_query_many(qs[short], top_k, flt)):
                    results[i] = exact
        return ([results[i] for i in range(len(qs))], stats,
                escalations)

    # -- deletes / persistence ----------------------------------------

    def delete(self, vec_ids: Sequence[str]) -> int:
        jaxmod, jnp = self._jax()
        n = 0
        with self._lock:
            zero_rows = []
            for vec_id in vec_ids:
                row = self._index.get(vec_id)
                if row is None or row in self._deleted_rows:
                    continue
                self._deleted_rows.add(row)
                self._unindex_meta(row)
                zero_rows.append(row)
                n += 1
            if zero_rows:
                self._host[zero_rows] = 0.0
            if zero_rows and self._device is not None:
                # ONE stacked row-zeroing patch (donated buffer), not a
                # scan step per row; indices bucket to a power of two
                # (duplicate writes of the same zero are idempotent) so
                # delete batch sizes share compiled programs.
                if self._zero_fn is None:
                    def zero(buf, rows):
                        return buf.at[rows].set(
                            jnp.zeros((), buf.dtype))
                    self._zero_fn = jaxmod.jit(zero, donate_argnums=(0,))
                idx = np.asarray(zero_rows, dtype=np.int32)
                b = next_pow2(len(idx))
                if b > len(idx):
                    idx = np.concatenate(
                        [idx, np.repeat(idx[:1], b - len(idx))])
                self._device = self._zero_fn(self._device,
                                             jnp.asarray(idx))
                self._device_rows = len(self._ids)
            if zero_rows and self._ivf is not None:
                self._ivf.remove(zero_rows)
        return n

    def delete_by_filter(self, flt):
        with self._lock:
            rows = self._filter_rows(flt)
            if rows is None:
                rows = [i for i, m in enumerate(self._metadata)
                        if i not in self._deleted_rows
                        and matches_filter(m, flt)]
            else:
                # Index candidates are a superset guess — re-verify
                # before anything irreversible.
                rows = [i for i in rows
                        if matches_filter(self._metadata[i], flt)]
            return self.delete([self._ids[i] for i in rows])

    def clear(self):
        with self._lock:
            self._ids.clear()
            self._index.clear()
            self._metadata.clear()
            self._init_inverted()
            self._deleted_rows.clear()
            self._host = None
            self._device = None
            self._device_rows = 0
            self._ivf = None
            self.last_query_stats = None

    def save(self, path: str | None = None) -> str:
        import json
        p = path or self.persist_path
        if not p:
            raise VectorStoreError("no persist_path configured")
        with self._lock:
            extra = {}
            if self._ivf is not None and self._ivf.trained:
                # the trained quantizer travels with the corpus; load()
                # rebuilds posting lists by deterministic assignment
                # (spill folds in), skipping the k-means re-fit
                extra["ivf_centroids"] = self._ivf.centroids_np
            np.savez_compressed(
                p,
                vectors=(self._host[:len(self._ids)]
                         if self._host is not None
                         else np.zeros((0, 0))),
                ids=np.array(self._ids, dtype=object),
                metadata=np.array(
                    [json.dumps(m) for m in self._metadata], dtype=object),
                deleted=np.array(sorted(self._deleted_rows)),
                **extra,
            )
        return p

    def load(self, path: str | None = None) -> int:
        """Bulk restore: rebuild the host state in one pass and ship
        the matrix with ONE device_put — not one add_embedding (and one
        device sync) per row. Deleted rows compact away; a saved
        trained quantizer is restored without re-running k-means."""
        import json
        p = path or self.persist_path
        if not p:
            raise VectorStoreError("no persist_path configured")
        data = np.load(p if str(p).endswith(".npz") else p + ".npz",
                       allow_pickle=True)
        with self._lock:
            self.clear()
            vectors = data["vectors"]
            ids = list(data["ids"])
            metas = [json.loads(m) for m in data["metadata"]]
            deleted = set(int(i) for i in data["deleted"])
            self._dim = int(vectors.shape[1]) if vectors.size else self._dim
            live = [i for i in range(len(ids)) if i not in deleted]
            if not live:
                return 0
            n = len(live)
            capacity = 16
            while capacity < n:
                capacity *= 2
            self._host = np.zeros((capacity, self._dim), dtype=np.float32)
            sub = vectors[live].astype(np.float32)
            norms = np.linalg.norm(sub, axis=1, keepdims=True)
            self._host[:n] = np.where(norms > 0,
                                      sub / np.maximum(norms, 1e-30), sub)
            self._ids = [str(ids[i]) for i in live]
            self._index = {vid: r for r, vid in enumerate(self._ids)}
            self._metadata = [metas[i] for i in live]
            for r, meta in enumerate(self._metadata):
                self._index_meta(r, meta)
            self._sync_device([], [])                # one device_put
            if self._index_kind == "ivf" and "ivf_centroids" in data:
                self._ensure_ivf().rebuild(
                    self._host, list(range(n)),
                    centroids=data["ivf_centroids"])
            return len(self._ids)


# ---------------------------------------------------------------------------
# shardcheck / hlocheck contracts (analysis/shardcheck.py, hlocheck.py)
# ---------------------------------------------------------------------------


@checkable("tpu-vectorstore")
def _shardcheck_tpu_vectorstore():
    """Build a tiny store far enough to materialize its lazily-jitted
    programs (an upsert after the first flush builds the patch program,
    a query builds the batched search, a delete builds the row-zeroing
    patch) and verify (a) the donated HBM matrix aliases its output in
    both mutating programs — this is the store's one long-lived device
    allocation, and a dropped alias would double it on every flush —
    and (b) the escalation ladder's power-of-two k bucketing keeps the
    query program cache bounded: four requested widths, two programs."""
    import functools

    import jax
    import jax.numpy as jnp

    dim = 8
    store = TPUVectorStore({"dimension": dim})
    store.add_embeddings([(f"v{i}", np.eye(dim)[i % dim], {"i": i})
                          for i in range(40)])
    store.add_embedding("v0", np.arange(dim, dtype=np.float32), {"i": 0})
    store.query([1.0] * dim, top_k=2)
    store.delete(["v1"])
    S = jax.ShapeDtypeStruct
    capacity = store._device.shape[0]
    matrix = S((capacity, dim), store._device.dtype)
    widths = (16, 48, 64, 256)       # escalation ladder requests
    variants = tuple(
        (f"w{w}",
         functools.partial(store._batch_query_fn,
                           k=min(capacity, next_pow2(w))),
         (matrix, S((2, dim), jnp.float32)))
        for w in widths)
    return [
        ContractCase(
            label="patch", fn=store._patch_fn,
            args=(matrix, S((1, dim), jnp.float32),
                  S((1,), jnp.int32)),
            donate_argnums=(0,)),
        ContractCase(
            label="delete-zero", fn=store._zero_fn,
            args=(matrix, S((2,), jnp.int32)),
            donate_argnums=(0,)),
        ContractCase(
            label="batch-query",
            fn=functools.partial(store._batch_query_fn, k=4),
            args=(matrix, S((2, dim), jnp.float32))),
        ContractCase(
            label="query-cache",
            hlo=HloSpec(variants=variants, expected_programs=2)),
    ]


@checkable("tpu-vectorstore-ivf")
def _shardcheck_tpu_vectorstore_ivf():
    """Single-device IVF contracts: train a tiny index and verify the
    posting-list maintenance programs donate their buffers (spill
    append and list-slot clear each patch one long-lived int32 buffer
    in place) and the fused search dispatch stays within its compiled
    peak-memory budget — the lax.map rescore bounds the candidate
    working set to one query's gather, and a regression to a
    [B, C, dim] materialization trips hlo-peak-memory."""
    import functools

    import jax
    import jax.numpy as jnp

    dim = 8
    store = TPUVectorStore({
        "dimension": dim, "index": "ivf", "ivf_min_train": 32,
        "ivf_nlist": 8, "ivf_nprobe": 4, "ivf_train_size": 64,
        "ivf_kmeans_iters": 2})
    rng = np.random.default_rng(0)
    store.add_embeddings([(f"v{i}", rng.normal(size=dim), {"i": i})
                          for i in range(48)])
    store.query([1.0] * dim, top_k=4)        # trains + search program
    store.add_embedding("s0", rng.normal(size=dim), {"i": -1})  # spill
    store.delete(["v1"])                     # list-slot clear
    ivf = store._ivf
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    cap = store._device.shape[0]
    lp, pad = (int(d) for d in ivf._d_rowids.shape)
    scap = int(ivf._d_spill.shape[0])
    return [
        ContractCase(
            label="ivf-search",
            fn=functools.partial(ivf._search_dispatch(), nprobe=4, k=8),
            args=(S((cap, dim), store._device.dtype), S((lp, dim), f32),
                  S((lp, pad), i32), S((scap,), i32), S((4, dim), f32)),
            hlo=HloSpec(peak_bytes=_IVF_SEARCH_PEAK_BUDGET)),
        ContractCase(
            label="ivf-spill-append", fn=ivf._patch1d_fn,
            args=(S((scap,), i32), S((4,), i32), S((4,), i32)),
            donate_argnums=(0,)),
        ContractCase(
            label="ivf-list-patch", fn=ivf._patch2d_fn,
            args=(S((lp, pad), i32), S((4,), i32), S((4,), i32),
                  S((4,), i32)),
            donate_argnums=(0,)),
    ]


@checkable("tpu-vectorstore-ivf-mesh")
def _shardcheck_tpu_vectorstore_ivf_mesh():
    """The sharded retrieval plane: posting lists and centroids
    partition over dp (slot counts are allocator-padded to divide
    evenly — the divisibility contract), and the fused per-shard search
    compiles with ZERO collectives — the cross-shard top-k reduction is
    a host merge over [B, dp*k], so a GSPMD reshard sneaking a gather
    into the hot dispatch turns the lane red."""
    import functools

    import jax
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.analysis.contracts import (
        require_devices,
    )

    require_devices(8)
    dim = 8
    store = TPUVectorStore({
        "dimension": dim, "index": "ivf", "mesh": 8,
        "ivf_min_train": 64, "ivf_nlist": 16, "ivf_nprobe": 2,
        "ivf_train_size": 128, "ivf_kmeans_iters": 2})
    rng = np.random.default_rng(0)
    store.add_embeddings([(f"v{i}", rng.normal(size=dim), {"i": i})
                          for i in range(96)])
    store.query([1.0] * dim, top_k=4)
    ivf = store._ivf
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    cap = store._device.shape[0]
    lp, pad = (int(d) for d in ivf._d_rowids.shape)
    scap = int(ivf._d_spill.shape[0])
    return [
        ContractCase(
            label="ivf-lists-partition", mesh=ivf.mesh,
            rules={"ivf_lists": "dp", "ivf_spill": "dp"},
            logical=(
                ("ivf-buffers",
                 {"rowids": S((lp, pad), i32),
                  "centroids": S((lp, dim), f32),
                  "spill": S((scap,), i32)},
                 {"rowids": ("ivf_lists", None),
                  "centroids": ("ivf_lists", None),
                  "spill": ("ivf_spill",)}),
            )),
        ContractCase(
            label="ivf-search-mesh",
            fn=functools.partial(ivf._search_dispatch(), nprobe=2, k=8),
            args=(S((cap, dim), store._device.dtype), S((lp, dim), f32),
                  S((lp, pad), i32), S((scap,), i32), S((8, dim), f32)),
            mesh=ivf.mesh,
            hlo=HloSpec(collectives={},
                        peak_bytes=_IVF_SEARCH_MESH_PEAK_BUDGET)),
    ]
