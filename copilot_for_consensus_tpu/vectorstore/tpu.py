"""On-device (TPU) exact-similarity vector store.

The role FAISS/Qdrant play for the reference
(``adapters/copilot_vectorstore/faiss_store.py:18,101-105``,
``qdrant_store.py:78``), redesigned for the chip: vectors live as one
HBM-resident [capacity, dim] matrix, a query is a single fused
``scores = M @ q`` matvec plus ``lax.top_k`` on the MXU/VPU — exact
cosine search at HBM bandwidth, no index build, no recall loss. 10M
384-dim bf16 vectors ≈ 7.4 GB: a v5e chip holds the whole corpus.

Filtered queries (``thread_id=...``) use a host-side inverted index over
metadata: highly selective filters score just the candidate rows on
host; broad filters run the device path with top-k oversampling.
Capacity grows geometrically; the device buffer is rebuilt on growth and
patched in place (jitted dynamic_update_slice) for small flushes.
"""

from __future__ import annotations

import threading
from typing import Any, Mapping, Sequence

import numpy as np

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    checkable,
)
from copilot_for_consensus_tpu.storage.base import matches_filter
from copilot_for_consensus_tpu.vectorstore._inverted import InvertedIndexMixin
from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)

_SELECTIVE_HOST_LIMIT = 4096     # filter hits below this → host-side scoring


class TPUVectorStore(InvertedIndexMixin, VectorStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self._dim: int | None = cfg.get("dimension") or None
        self._dtype_name = cfg.get("dtype", "bfloat16")
        self.persist_path = cfg.get("persist_path")
        self._lock = threading.RLock()
        self._ids: list[str] = []
        self._index: dict[str, int] = {}
        self._metadata: list[dict[str, Any]] = []
        self._host: np.ndarray | None = None        # [n, dim] fp32 master
        self._init_inverted()
        self._device = None                          # [capacity, dim]
        self._device_rows = 0                        # rows synced
        self._deleted_rows: set[int] = set()
        self._batch_query_fn = None
        self._patch_fn = None

    # -- lazy jax ------------------------------------------------------

    def _jax(self):
        import jax
        import jax.numpy as jnp
        return jax, jnp

    @property
    def dimension(self) -> int | None:
        with self._lock:
            return self._dim

    def count(self) -> int:
        with self._lock:
            return len(self._ids) - len(self._deleted_rows)

    # -- writes --------------------------------------------------------

    def add_embedding(self, vec_id, vector, metadata=None):
        self.add_embeddings([(vec_id, vector, metadata)])

    def add_embeddings(self, items) -> int:
        jaxmod, jnp = self._jax()
        n = 0
        with self._lock:
            rows, vecs = [], []
            for vec_id, vector, metadata in items:
                arr = np.asarray(vector, dtype=np.float32)
                if self._dim is None:
                    self._dim = int(arr.shape[0])
                if arr.shape[0] != self._dim:
                    raise VectorStoreError(
                        f"dimension mismatch: {arr.shape[0]} != {self._dim}")
                norm = float(np.linalg.norm(arr))
                if norm > 0:
                    arr = arr / norm
                meta = dict(metadata or {})
                if vec_id in self._index:            # upsert semantics
                    row = self._index[vec_id]
                    self._unindex_meta(row)
                    self._host[row] = arr
                    self._metadata[row] = meta
                    self._index_meta(row, meta)
                    self._deleted_rows.discard(row)
                    rows.append(row)
                    vecs.append(arr)
                else:
                    row = len(self._ids)
                    self._ids.append(vec_id)
                    self._index[vec_id] = row
                    self._metadata.append(meta)
                    self._index_meta(row, meta)
                    self._append_host(arr)
                    rows.append(row)
                    vecs.append(arr)
                n += 1
            self._sync_device(rows, vecs)
        return n

    def _append_host(self, arr: np.ndarray) -> None:
        if self._host is None:
            self._host = np.zeros((16, self._dim), dtype=np.float32)
        if len(self._ids) > self._host.shape[0]:
            grown = np.zeros((self._host.shape[0] * 2, self._dim),
                             dtype=np.float32)
            grown[:self._host.shape[0]] = self._host
            self._host = grown
        self._host[len(self._ids) - 1] = arr

    def _unindex_meta(self, row: int) -> None:
        meta = self._metadata[row]
        for k, v in meta.items():
            if isinstance(v, (str, int, bool)):
                self._inverted[(k, v)].discard(row)

    def _sync_device(self, rows: list[int], vecs: list[np.ndarray]) -> None:
        """Patch the device buffer; rebuild on growth."""
        jaxmod, jnp = self._jax()
        dtype = getattr(jnp, self._dtype_name)
        capacity = self._host.shape[0] if self._host is not None else 0
        if (self._device is None
                or self._device.shape[0] != capacity):
            self._device = jaxmod.device_put(
                self._host.astype(np.float32)).astype(dtype)
            self._device_rows = len(self._ids)
            return
        if not rows:
            return
        if self._patch_fn is None:
            def patch(buf, updates, starts):
                def one(buf, pair):
                    vec, start = pair
                    return jaxmod.lax.dynamic_update_slice(
                        buf, vec.astype(buf.dtype)[None, :],
                        (start, 0)), None
                buf, _ = jaxmod.lax.scan(one, buf, (updates, starts))
                return buf
            self._patch_fn = jaxmod.jit(patch, donate_argnums=(0,))
        self._device = self._patch_fn(
            self._device, jnp.asarray(np.stack(vecs), dtype=jnp.float32),
            jnp.asarray(rows, dtype=jnp.int32))
        self._device_rows = len(self._ids)

    # -- reads ---------------------------------------------------------

    def get(self, vec_id):
        with self._lock:
            row = self._index.get(vec_id)
            if row is None or row in self._deleted_rows:
                return None
            return self._host[row].tolist(), dict(self._metadata[row])

    def query(self, vector, top_k: int = 10, flt=None):
        with self._lock:
            n = len(self._ids)
            if n == 0 or self._dim is None:
                return []
            q = np.asarray(vector, dtype=np.float32)
            norm = float(np.linalg.norm(q))
            if norm > 0:
                q = q / norm

            if flt:
                cand = self._filter_rows(flt)
                if cand is not None and len(cand) <= _SELECTIVE_HOST_LIMIT:
                    return self._host_query(q, cand, top_k, flt)
            return self._device_query(q, top_k, flt)

    def query_batch(self, vectors, top_k: int = 10, flt=None):
        """B queries in ONE device dispatch: [B, D] @ HBM matrixᵀ with a
        per-row top-k. Single queries over the tunnel are round-trip
        latency-bound (~5 QPS measured at 100k×384); batching moves the
        store to compute-bound territory (~1000 QPS at batch 256)."""
        with self._lock:
            n = len(self._ids)
            if n == 0 or self._dim is None:
                return [[] for _ in vectors]
            qs = np.asarray(list(vectors), dtype=np.float32)
            norms = np.linalg.norm(qs, axis=1, keepdims=True)
            qs = np.where(norms > 0, qs / np.maximum(norms, 1e-30), qs)
            if flt:
                cand = self._filter_rows(flt)
                if cand is not None and len(cand) <= _SELECTIVE_HOST_LIMIT:
                    return [self._host_query(q, cand, top_k, flt)
                            for q in qs]
            return self._device_query_many(qs, top_k, flt)

    def _filter_rows(self, flt: Mapping[str, Any]) -> list[int] | None:
        """Candidate rows via the shared inverted index (superset guess;
        callers re-verify with matches_filter); None = not decidable."""
        cand = self._filter_candidates(flt)
        if cand is None:
            return None
        return sorted(cand - self._deleted_rows)

    def _host_query(self, q, rows: list[int], top_k: int, flt):
        if not rows:
            return []
        sub = self._host[rows]                       # [m, dim]
        scores = sub @ q
        order = np.argsort(-scores)[:top_k]
        return [
            QueryResult(self._ids[rows[i]], float(scores[i]),
                        dict(self._metadata[rows[i]]))
            for i in order
            if matches_filter(self._metadata[rows[i]], flt)
        ]

    def _device_query(self, q, top_k: int, flt):
        return self._device_query_many(np.asarray(q, np.float32)[None],
                                       top_k, flt)[0]

    def _device_query_many(self, qs: np.ndarray, top_k: int, flt
                           ) -> list[list[QueryResult]]:
        """ONE implementation for single and batched device search:
        fused [B, D] @ matrixᵀ + per-row top-k, with top-k oversampling
        escalation for filtered/deleted rows. Escalation rounds rescore
        only the still-pending queries, and stop once k covers every
        live-or-dead row ever added (``len(self._ids)`` — deletes keep
        their id slot, so that IS the row count)."""
        jaxmod, jnp = self._jax()
        if self._batch_query_fn is None:
            def run(matrix, qv, k):
                scores = (qv.astype(matrix.dtype)
                          @ matrix.T).astype(jnp.float32)
                return jaxmod.lax.top_k(scores, k)       # [B, k] each
            self._batch_query_fn = jaxmod.jit(run, static_argnames=("k",))

        capacity = self._device.shape[0]
        oversample = max(top_k, 16)
        pending = list(range(len(qs)))
        results: dict[int, list[QueryResult]] = {}
        while True:
            k = min(capacity, oversample)
            vals, idx = self._batch_query_fn(
                self._device, jnp.asarray(qs[pending]), k)
            vals = np.asarray(vals)
            idx = np.asarray(idx)
            still = []
            for bi, qi in enumerate(pending):
                out = []
                for score, row in zip(vals[bi], idx[bi]):
                    row = int(row)
                    if row >= len(self._ids) or row in self._deleted_rows:
                        continue  # padding rows score ~0; skip
                    meta = self._metadata[row]
                    if flt and not matches_filter(meta, flt):
                        continue
                    out.append(QueryResult(self._ids[row], float(score),
                                           dict(meta)))
                    if len(out) == top_k:
                        break
                results[qi] = out
                if (len(out) < top_k and k < capacity
                        and k < len(self._ids)):
                    still.append(qi)
            if not still:
                return [results[i] for i in range(len(qs))]
            pending = still
            oversample *= 4

    # -- deletes / persistence ----------------------------------------

    def delete(self, vec_ids: Sequence[str]) -> int:
        jaxmod, jnp = self._jax()
        n = 0
        with self._lock:
            zero_rows = []
            for vec_id in vec_ids:
                row = self._index.get(vec_id)
                if row is None or row in self._deleted_rows:
                    continue
                self._deleted_rows.add(row)
                self._unindex_meta(row)
                self._host[row] = 0.0
                zero_rows.append(row)
                n += 1
            if zero_rows and self._device is not None:
                self._sync_device(zero_rows,
                                  [np.zeros(self._dim, dtype=np.float32)
                                   for _ in zero_rows])
        return n

    def delete_by_filter(self, flt):
        with self._lock:
            rows = self._filter_rows(flt)
            if rows is None:
                rows = [i for i, m in enumerate(self._metadata)
                        if i not in self._deleted_rows
                        and matches_filter(m, flt)]
            else:
                # Index candidates are a superset guess — re-verify
                # before anything irreversible.
                rows = [i for i in rows
                        if matches_filter(self._metadata[i], flt)]
            return self.delete([self._ids[i] for i in rows])

    def clear(self):
        with self._lock:
            self._ids.clear()
            self._index.clear()
            self._metadata.clear()
            self._init_inverted()
            self._deleted_rows.clear()
            self._host = None
            self._device = None
            self._device_rows = 0

    def save(self, path: str | None = None) -> str:
        import json
        p = path or self.persist_path
        if not p:
            raise VectorStoreError("no persist_path configured")
        with self._lock:
            np.savez_compressed(
                p,
                vectors=(self._host[:len(self._ids)]
                         if self._host is not None
                         else np.zeros((0, 0))),
                ids=np.array(self._ids, dtype=object),
                metadata=np.array(
                    [json.dumps(m) for m in self._metadata], dtype=object),
                deleted=np.array(sorted(self._deleted_rows)),
            )
        return p

    def load(self, path: str | None = None) -> int:
        import json
        p = path or self.persist_path
        if not p:
            raise VectorStoreError("no persist_path configured")
        data = np.load(p if str(p).endswith(".npz") else p + ".npz",
                       allow_pickle=True)
        with self._lock:
            self.clear()
            vectors = data["vectors"]
            ids = list(data["ids"])
            metas = [json.loads(m) for m in data["metadata"]]
            deleted = set(int(i) for i in data["deleted"])
            self._dim = int(vectors.shape[1]) if vectors.size else self._dim
            for i, (vid, meta) in enumerate(zip(ids, metas)):
                if i in deleted:
                    continue
                self.add_embedding(str(vid), vectors[i], meta)
            return len(self._ids)


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("tpu-vectorstore")
def _shardcheck_tpu_vectorstore():
    """Build a tiny store far enough to materialize its two lazily-jitted
    programs (an upsert after the first flush builds the patch program,
    a query builds the batched search) and verify the patch program's
    donated HBM matrix aliases its output — this is the store's one
    long-lived device allocation, and a dropped alias would double it
    on every small flush."""
    import functools

    import jax
    import jax.numpy as jnp

    dim = 8
    store = TPUVectorStore({"dimension": dim})
    store.add_embeddings([(f"v{i}", np.eye(dim)[i % dim], {"i": i})
                          for i in range(3)])
    store.add_embedding("v0", np.arange(dim, dtype=np.float32), {"i": 0})
    store.query([1.0] * dim, top_k=2)
    S = jax.ShapeDtypeStruct
    capacity = store._device.shape[0]
    matrix = S((capacity, dim), store._device.dtype)
    return [
        ContractCase(
            label="patch", fn=store._patch_fn,
            args=(matrix, S((1, dim), jnp.float32),
                  S((1,), jnp.int32)),
            donate_argnums=(0,)),
        ContractCase(
            label="batch-query",
            fn=functools.partial(store._batch_query_fn, k=4),
            args=(matrix, S((2, dim), jnp.float32))),
    ]
