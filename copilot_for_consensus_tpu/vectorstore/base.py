"""VectorStore ABC."""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Sequence


class VectorStoreError(Exception):
    pass


@dataclass
class QueryResult:
    id: str
    score: float
    metadata: dict[str, Any] = field(default_factory=dict)


class VectorStore(abc.ABC):
    """Embedding storage with upsert semantics and metadata-filtered top-k.

    Scores are cosine similarity in [-1, 1]; higher is better.
    """

    def connect(self) -> None:
        pass

    def close(self) -> None:
        pass

    def set_metrics(self, collector: Any) -> None:
        """Wire a MetricsCollector so the store can emit retrieval
        telemetry (``vectorstore_*`` series). Default: drop it —
        drivers without native metrics stay silent."""

    @abc.abstractmethod
    def add_embedding(self, vec_id: str, vector: Sequence[float],
                      metadata: Mapping[str, Any] | None = None) -> None: ...

    def add_embeddings(self, items: Iterable[tuple[str, Sequence[float],
                                                   Mapping[str, Any] | None]]) -> int:
        n = 0
        for vec_id, vector, metadata in items:
            self.add_embedding(vec_id, vector, metadata)
            n += 1
        return n

    @abc.abstractmethod
    def query(self, vector: Sequence[float], top_k: int = 10,
              flt: Mapping[str, Any] | None = None) -> list[QueryResult]: ...

    def query_batch(self, vectors: Sequence[Sequence[float]],
                    top_k: int = 10,
                    flt: Mapping[str, Any] | None = None
                    ) -> list[list[QueryResult]]:
        """Many queries at once. The base implementation loops; device
        drivers override it with one fused dispatch — on hardware where
        each dispatch costs a host↔device round trip, this is the
        difference between latency-bound and compute-bound search."""
        return [self.query(v, top_k, flt) for v in vectors]

    @abc.abstractmethod
    def get(self, vec_id: str) -> tuple[list[float], dict[str, Any]] | None: ...

    @abc.abstractmethod
    def delete(self, vec_ids: Sequence[str]) -> int: ...

    def delete_by_filter(self, flt: Mapping[str, Any]) -> int:
        """Delete every vector whose metadata matches ``flt``. Drivers
        that can't filter server-side may override or raise."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support filtered deletion")

    @abc.abstractmethod
    def count(self) -> int: ...

    @abc.abstractmethod
    def clear(self) -> None: ...

    @property
    @abc.abstractmethod
    def dimension(self) -> int | None:
        """Vector dimension, or None until the first vector is added."""
