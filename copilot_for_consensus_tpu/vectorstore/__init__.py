"""Vector store abstraction: embedding storage + top-k similarity search.

Capability parity with the reference's ``copilot_vectorstore`` package
(ABC ``interface.py:28-126``; Qdrant/FAISS/InMemory/AzureAISearch drivers —
SURVEY.md §2.1). Drivers here:

* ``memory`` — numpy exact search (tests, small corpora);
* ``tpu``    — on-device ANN: HBM-resident vectors, sharded matmul top-k
  under jit (``ann/``), the north-star replacement for Qdrant/FAISS;
* ``native`` — C++ flat index via ctypes for host-side search without a
  device (fills the FAISS role).

All drivers upsert on add (idempotent re-embedding, reference
``interface.py:40-42``).
"""

from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)
from copilot_for_consensus_tpu.vectorstore.memory import InMemoryVectorStore
from copilot_for_consensus_tpu.vectorstore.factory import create_vector_store

__all__ = [
    "QueryResult",
    "VectorStore",
    "VectorStoreError",
    "InMemoryVectorStore",
    "create_vector_store",
]
