"""Azure AI Search vector store — raw REST, no SDK.

Fills the role of the reference's
``copilot_vectorstore/azure_ai_search_store.py:32``
(AzureAISearchVectorStore: HNSW index provisioning ``:255``, vector
query with metadata, mergeOrUpload batching) with the documented
Search REST API and stdlib HTTP only, in the repo's Azure-driver
convention: the same requests work against real Azure AI Search or the
in-process wire-contract mock (``tests/test_azure_ai_search.py``).

Index shape (provisioned on connect, mirroring the reference's):

* ``id`` — key, filterable;
* ``embedding`` — ``Collection(Edm.Single)`` with the HNSW profile
  (m=4, efConstruction=400, efSearch=500, metric=cosine — the
  reference's constants ``azure_ai_search_store.py:23-29``);
* ``metadata`` — full metadata dict as one JSON string (retrievable);
* one filterable ``Edm.String`` field per configured
  ``filterable_keys`` entry — what makes server-side ``flt`` pushdown
  possible (the pipeline filters on ``thread_id``,
  ``services/orchestrator.py:130``).

Scores: AI Search reports ``@search.score = 1/(1 + d)`` with
``d = 1 - cosine``; the driver converts back to the base contract's
cosine-in-[-1, 1] (``vectorstore/base.py:24``).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Mapping, Sequence

from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)

API_VERSION = "2023-11-01"
# reference azure_ai_search_store.py:23-29
HNSW_M = 4
HNSW_EF_CONSTRUCTION = 400
HNSW_EF_SEARCH = 500

DEFAULT_FILTERABLE_KEYS = ("thread_id", "archive_id", "chunk_id",
                           "message_doc_id")


def _odata_quote(value: Any) -> str:
    return "'" + str(value).replace("'", "''") + "'"


def _odata_any_of(key: str, values: Sequence[Any]) -> str:
    """Membership as an eq-or chain. ``search.in`` would be fewer bytes
    but splits on its delimiter, silently mis-matching any value that
    contains it (ids are arbitrary strings per the base contract)."""
    return ("(" + " or ".join(
        f"{key} eq {_odata_quote(v)}" for v in values) + ")")


#: sentinel returned by _translate_filter for a filter that can match
#: nothing (empty $in) — callers short-circuit without a wire call
EMPTY_MATCH = object()


class AzureAISearchVectorStore(VectorStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self.endpoint = str(cfg.get("endpoint", "")).rstrip("/")
        self.api_key = str(cfg.get("api_key", ""))
        self.index_name = str(cfg.get("index_name", "embeddings"))
        self._dimension = int(cfg.get("dimension", 0))
        self.filterable_keys = tuple(
            cfg.get("filterable_keys") or DEFAULT_FILTERABLE_KEYS)
        self.timeout_s = float(cfg.get("timeout_s", 30.0))
        if not self.endpoint:
            raise ValueError("azure_ai_search needs endpoint")
        if not self.api_key:
            raise ValueError("azure_ai_search needs api_key")
        if self._dimension <= 0:
            raise ValueError(
                "azure_ai_search needs dimension > 0 (the index's "
                "vector field is fixed-size)")
        self._connected = False

    # -- wire plumbing --------------------------------------------------

    def _request(self, method: str, path: str,
                 body: dict | None = None,
                 ok: tuple[int, ...] = (200, 201, 204)
                 ) -> tuple[int, Any]:
        url = (f"{self.endpoint}{path}"
               f"{'&' if '?' in path else '?'}api-version={API_VERSION}")
        req = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"api-key": self.api_key,
                     "Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                raw = resp.read()
                return resp.status, (json.loads(raw) if raw else None)
        except urllib.error.HTTPError as exc:
            if exc.code in ok:
                raw = exc.read()
                return exc.code, (json.loads(raw) if raw else None)
            detail = exc.read()[:200].decode("utf-8", "replace")
            raise VectorStoreError(
                f"ai_search {method} {path} failed: HTTP {exc.code} "
                f"{detail}") from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise VectorStoreError(
                f"ai_search unreachable at {self.endpoint}: {exc}"
            ) from exc

    # -- index lifecycle ------------------------------------------------

    def _index_definition(self) -> dict[str, Any]:
        fields: list[dict[str, Any]] = [
            {"name": "id", "type": "Edm.String", "key": True,
             "filterable": True},
            {"name": "embedding", "type": "Collection(Edm.Single)",
             "searchable": True, "dimensions": self._dimension,
             "vectorSearchProfile": "vp"},
            {"name": "metadata", "type": "Edm.String",
             "retrievable": True},
        ]
        fields += [{"name": k, "type": "Edm.String",
                    "filterable": True}
                   for k in self.filterable_keys]
        return {
            "name": self.index_name,
            "fields": fields,
            "vectorSearch": {
                "algorithms": [{
                    "name": "hnsw-algorithm", "kind": "hnsw",
                    "hnswParameters": {
                        "m": HNSW_M,
                        "efConstruction": HNSW_EF_CONSTRUCTION,
                        "efSearch": HNSW_EF_SEARCH,
                        "metric": "cosine",
                    },
                }],
                "profiles": [{"name": "vp",
                              "algorithm": "hnsw-algorithm"}],
            },
        }

    def connect(self) -> None:
        self._request(
            "PUT",
            f"/indexes/{urllib.parse.quote(self.index_name)}",
            self._index_definition())
        self._connected = True

    def _ensure(self) -> None:
        if not self._connected:
            self.connect()

    def _docs_path(self, suffix: str) -> str:
        return (f"/indexes/{urllib.parse.quote(self.index_name)}"
                f"/docs{suffix}")

    # -- write path -----------------------------------------------------

    def _to_doc(self, vec_id: str, vector: Sequence[float],
                metadata: Mapping[str, Any] | None) -> dict[str, Any]:
        if len(vector) != self._dimension:
            raise VectorStoreError(
                f"vector for {vec_id!r} has dimension {len(vector)}, "
                f"index expects {self._dimension}")
        md = dict(metadata or {})
        doc = {"@search.action": "mergeOrUpload", "id": str(vec_id),
               "embedding": [float(x) for x in vector],
               "metadata": json.dumps(md)}
        for k in self.filterable_keys:
            if k in md:
                doc[k] = str(md[k])
        return doc

    def add_embedding(self, vec_id, vector, metadata=None) -> None:
        self.add_embeddings([(vec_id, vector, metadata)])

    def add_embeddings(self, items) -> int:
        self._ensure()
        docs = [self._to_doc(i, v, m) for i, v, m in items]
        if not docs:
            return 0
        n = 0
        # the service caps batches at 1000 actions
        for start in range(0, len(docs), 1000):
            batch = docs[start:start + 1000]
            _, out = self._request("POST", self._docs_path("/index"),
                                   {"value": batch}, ok=(200, 207))
            for result in (out or {}).get("value", []):
                if not result.get("status", False):
                    raise VectorStoreError(
                        f"ai_search upsert failed for "
                        f"{result.get('key')!r}: "
                        f"{result.get('errorMessage')}")
                n += 1
        return n

    # -- read path ------------------------------------------------------

    def _translate_filter(self, flt: Mapping[str, Any] | None
                          ) -> str | None:
        """Base-contract filters → OData. Only keys promoted to
        filterable index fields can be pushed down; anything else is a
        loud error, not a silent wrong answer."""
        if not flt:
            return None
        terms = []
        for key, cond in flt.items():
            if key not in self.filterable_keys:
                raise VectorStoreError(
                    f"filter key {key!r} is not in filterable_keys "
                    f"{self.filterable_keys}; add it to the driver "
                    "config (re-indexing required)")
            if isinstance(cond, Mapping):
                if set(cond) == {"$in"}:
                    vals = [str(v) for v in cond["$in"]]
                    if not vals:
                        return EMPTY_MATCH   # sentinel: matches nothing
                    terms.append(_odata_any_of(key, vals))
                    continue
                raise VectorStoreError(
                    f"unsupported ai_search filter operator(s) "
                    f"{sorted(cond)} for {key!r} (supported: equality, "
                    "$in)")
            else:
                terms.append(f"{key} eq {_odata_quote(cond)}")
        return " and ".join(terms)

    @staticmethod
    def _score_to_cosine(score: float) -> float:
        # @search.score = 1 / (1 + d), d = 1 - cosine
        if score <= 0:
            return -1.0
        return 2.0 - 1.0 / score

    def query(self, vector, top_k=10, flt=None) -> list[QueryResult]:
        self._ensure()
        if len(vector) != self._dimension:
            raise VectorStoreError(
                f"query vector has dimension {len(vector)}, index "
                f"expects {self._dimension}")
        body: dict[str, Any] = {
            "search": "",
            "select": "id,metadata",
            "top": top_k,
            "vectorQueries": [{
                "kind": "vector",
                "vector": [float(x) for x in vector],
                "fields": "embedding",
                "k": top_k,
            }],
        }
        odata = self._translate_filter(flt)
        if odata is EMPTY_MATCH:
            return []
        if odata:
            body["filter"] = odata
        _, out = self._request("POST", self._docs_path("/search"), body)
        results = []
        for row in (out or {}).get("value", []):
            try:
                md = json.loads(row.get("metadata") or "{}")
            except ValueError:
                md = {}
            results.append(QueryResult(
                row["id"], self._score_to_cosine(
                    float(row["@search.score"])), md))
        return results

    def get(self, vec_id):
        self._ensure()
        # OData key literal: single quotes double FIRST, then
        # percent-encode — encoding alone would decode server-side into
        # a literal terminator and 400
        quoted = urllib.parse.quote(
            str(vec_id).replace("'", "''"), safe="")
        status, out = self._request(
            "GET", self._docs_path(f"('{quoted}')"), ok=(200, 404))
        if status == 404 or out is None:
            return None
        try:
            md = json.loads(out.get("metadata") or "{}")
        except ValueError:
            md = {}
        return [float(x) for x in out.get("embedding") or []], md

    def delete(self, vec_ids) -> int:
        """Delete by id; returns the number that existed.

        The count is BEST-EFFORT under the service's eventual
        consistency: the index API reports statusCode 200 for absent
        keys too, so existence is probed with a pre-delete search —
        documents added moments ago may not be searchable yet
        (under-count), and concurrent deleters can both observe a doc
        (double-count). Exact-count callers must serialize externally.
        """
        self._ensure()
        ids = [str(i) for i in vec_ids]
        if not ids:
            return 0
        existing = 0
        for start in range(0, len(ids), 64):
            chunk = ids[start:start + 64]
            _, out = self._request(
                "POST", self._docs_path("/search"),
                {"search": "", "filter": _odata_any_of("id", chunk),
                 "select": "id", "top": len(chunk), "count": True})
            existing += int((out or {}).get("@odata.count",
                                            len((out or {}).get(
                                                "value", []))))
        actions = [{"@search.action": "delete", "id": i} for i in ids]
        self._request("POST", self._docs_path("/index"),
                      {"value": actions}, ok=(200, 207))
        return existing

    def delete_by_filter(self, flt) -> int:
        self._ensure()
        odata = self._translate_filter(flt)
        if odata is EMPTY_MATCH:
            return 0
        # the service indexes asynchronously: a search issued right
        # after a delete batch can still return the same ids. Count
        # UNIQUE ids and stop when a round surfaces nothing new, so
        # eventual consistency can neither over-report nor spin forever.
        seen: set[str] = set()
        while True:
            _, out = self._request(
                "POST", self._docs_path("/search"),
                {"search": "", "filter": odata, "select": "id",
                 "top": 1000})
            ids = [row["id"] for row in (out or {}).get("value", [])]
            fresh = [i for i in ids if i not in seen]
            if not fresh:
                return len(seen)
            seen.update(fresh)
            self._request(
                "POST", self._docs_path("/index"),
                {"value": [{"@search.action": "delete", "id": i}
                           for i in fresh]}, ok=(200, 207))

    def count(self) -> int:
        self._ensure()
        _, out = self._request("GET", self._docs_path("/$count"))
        return int(out)

    def clear(self) -> None:
        self._request(
            "DELETE",
            f"/indexes/{urllib.parse.quote(self.index_name)}",
            ok=(200, 204, 404))
        self._connected = False
        self.connect()

    @property
    def dimension(self) -> int | None:
        return self._dimension
