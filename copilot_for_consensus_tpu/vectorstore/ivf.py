"""Two-tier IVF index over the TPU vector store's HBM matrix.

The sub-linear retrieval stage ROADMAP item 1 asks for: instead of
scoring the whole ``[capacity, dim]`` matrix per query (exact flat
search, O(corpus)), a k-means coarse quantizer routes each query to
``nprobe`` posting lists and only those lists' rows are rescored
exactly against the SAME HBM matrix the flat route scores. The index
therefore adds only int32 posting lists and a small centroid matrix on
top of the store's one long-lived vector allocation — upserts/deletes
keep mutating the matrix exactly as the flat route does, and the lists
only say *where to look*.

Layout (the PR-15 per-dp-shard allocator pattern, applied to lists):

* centroids live as ``[nlist_padded, dim]`` f32, posting lists as
  ``[nlist_padded, pad]`` int32 global row ids (``-1`` = empty slot);
  both are sharded over the mesh's ``dp`` axis when a mesh is given —
  shard ``s`` owns slot rows ``[s*sps, (s+1)*sps)``, and a host-side
  :class:`ListShardAllocator` (LPT greedy over list sizes) decides
  which k-means list lands in which shard's slots so row totals
  balance.
* the fused search dispatch runs per shard (``shard_map`` over dp):
  centroid scores → top-``nprobe`` local lists → gather candidate row
  ids → gather candidate vectors from the (replicated) matrix → exact
  rescore → shard-local top-k. Outputs stack ``[B, k]`` per shard into
  ``[B, dp*k]`` with NO collective — the cross-shard top-k reduction
  happens on host over ``dp*k`` candidates per query (k ≪ corpus, so
  the host merge is noise).
* rows added after a (re)train append into a SPILL block — a sharded
  ``[spill_cap]`` int32 id list scored exactly on every query — so
  ``add_embeddings`` never blocks on an index rebuild; the spill folds
  into posting lists at the next retrain.

Retrain policy (lazy, checked on the query path, never on ingest):

* first train once the live corpus reaches ``min_train`` rows;
* retrain when the spill fraction (spill rows / live rows) crosses
  ``spill_fraction`` — this is also how centroid-imbalance drift
  surfaces, because a list that outgrows its padded capacity
  overflows into the spill;
* retrain when the corpus outgrows the trained size by
  ``growth_factor`` (nlist is re-picked from the new corpus size).

Import stays jax-free (the analysis CLI imports the vectorstore
package on machines without jax); all device work is lazy.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

#: queries rescored together inside the fused search (lax.map
#: batch_size): bounds the candidate working set to
#: [_RESCORE_GROUP, C, dim] while amortizing per-query dispatch —
#: 1 serializes the batch (10x batched-QPS loss measured at 1M), the
#: full batch materializes [B, C, dim] (512MB at B=64, C=32k, dim=64)
_RESCORE_GROUP = 8


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (>=1)."""
    return 1 << max(0, int(n - 1).bit_length()) if n > 1 else 1


@dataclass
class IVFParams:
    """Tuning knobs; every field has a serving-sane default."""

    nlist: int = 0              # 0 = auto: ~sqrt(n), pow2, in [8, 4096]
    nprobe: int = 8             # lists probed per shard per query
    train_size: int = 65536     # k-means sample = first N live rows
    min_train: int = 256        # corpus size that triggers first train
    kmeans_iters: int = 8
    spill_fraction: float = 0.25   # spill/live ratio forcing a retrain
    growth_factor: float = 2.0     # corpus growth forcing a retrain
    pad_factor: float = 4.0        # list capacity ≈ pad_factor * mean
    seed: int = 0

    @staticmethod
    def from_config(cfg: dict) -> "IVFParams":
        p = IVFParams()
        for f, cast in (("nlist", int), ("nprobe", int),
                        ("train_size", int), ("min_train", int),
                        ("kmeans_iters", int), ("spill_fraction", float),
                        ("growth_factor", float), ("pad_factor", float),
                        ("seed", int)):
            key = f"ivf_{f}"
            if key in cfg:
                setattr(p, f, cast(cfg[key]))
        return p


class ListShardAllocator:
    """Assign posting lists to dp shards balancing row totals.

    The PR-15 block-pool discipline applied to lists: the host owns
    placement, the device sees per-shard slot ranges. LPT greedy
    (largest list first, onto the shard with the least rows that still
    has a free slot) keeps per-shard scan work within ~2x of perfect
    balance; every shard gets exactly ``slots_per_shard`` slots so the
    slot axis divides evenly over dp — the divisibility contract the
    shardcheck case declares.
    """

    def __init__(self, num_shards: int, nlist: int):
        self.num_shards = int(num_shards)
        self.slots_per_shard = max(
            1, math.ceil(nlist / max(1, num_shards)))

    def assign(self, sizes: np.ndarray) -> np.ndarray:
        """``sizes[l]`` = rows in list l → global device slot per list.

        Shard s owns slots ``[s*sps, (s+1)*sps)``; unassigned slots are
        padding (zero centroid, all-empty list).
        """
        sps = self.slots_per_shard
        order = np.argsort(-sizes, kind="stable")
        load = np.zeros(self.num_shards, dtype=np.int64)
        used = np.zeros(self.num_shards, dtype=np.int64)
        slot_of_list = np.full(len(sizes), -1, dtype=np.int64)
        for l in order:
            open_shards = np.flatnonzero(used < sps)
            s = open_shards[np.argmin(load[open_shards])]
            slot_of_list[l] = s * sps + used[s]
            used[s] += 1
            load[s] += sizes[l]
        return slot_of_list


class IVFIndex:
    """The device-side index: centroids + posting lists + spill block.

    Holds GLOBAL row ids only; candidate vectors gather from the
    store's HBM matrix at query time, so the store's single vector
    allocation stays the one source of truth for every byte of vector
    data (upserted vectors rescore correctly even before the index
    catches up, because the rescore reads the live matrix).
    """

    def __init__(self, dim: int, params: IVFParams | None = None,
                 mesh: Any = None):
        self.dim = int(dim)
        self.params = params or IVFParams()
        self.mesh = mesh
        self.num_shards = (int(mesh.shape["dp"])
                           if mesh is not None else 1)
        self.trained = False
        self.generation = 0
        self.nlist = 0               # real (unpadded) list count
        self.pad = 0                 # per-list slot capacity
        self.sps = 0                 # list slots per shard
        self.trained_at_n = 0
        self.overflow_count = 0      # rows a full list pushed to spill
        self.centroids_np: np.ndarray | None = None  # [nlist, dim]
        self._locator: dict[int, tuple] = {}  # row -> ("l",slot,off)|("s",pos)
        self._d_centroids = None     # [nlist_padded, dim] f32 (dp)
        self._d_rowids = None        # [nlist_padded, pad] i32 (dp)
        self._d_spill = None         # [spill_cap] i32 (dp)
        self._spill_n = 0            # high-water append cursor
        self._spill_live = 0
        self._indexed_live = 0
        self._kmeans_fn = None
        self._assign_fn = None
        self._search_fn = None
        self._patch1d_fn = None
        self._patch2d_fn = None

    # -- lazy jax ------------------------------------------------------

    def _jax(self):
        import jax
        import jax.numpy as jnp
        return jax, jnp

    def _put(self, arr: np.ndarray, spec_axes: tuple):
        """device_put, sharded over dp when a mesh is present."""
        jax, _ = self._jax()
        if self.mesh is None:
            return jax.device_put(arr)
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(
            arr, NamedSharding(self.mesh, PartitionSpec(*spec_axes)))

    # -- sizing --------------------------------------------------------

    def auto_nlist(self, n: int) -> int:
        if self.params.nlist:
            return min(self.params.nlist, max(1, n))
        return max(8, min(4096, next_pow2(int(math.sqrt(max(1, n))))))

    @property
    def live_count(self) -> int:
        return self._indexed_live + self._spill_live

    def spill_frac(self) -> float:
        live = self.live_count
        return (self._spill_live / live) if live else 0.0

    def needs_retrain(self, live_n: int) -> bool:
        if not self.trained:
            return live_n >= self.params.min_train
        if live_n < 1:
            return False
        if self.spill_frac() > self.params.spill_fraction:
            return True
        return live_n >= self.params.growth_factor * self.trained_at_n

    def max_candidates(self, nprobe: int | None = None) -> int:
        """Rows one query can reach — the escalation ceiling: probed
        list capacity plus the whole spill block, summed over shards."""
        if not self.trained:
            return 0
        npb = min(nprobe if nprobe is not None else self.params.nprobe,
                  self.sps)
        spill_cap = (int(self._d_spill.shape[0])
                     if self._d_spill is not None else 0)
        return self.num_shards * npb * self.pad + spill_cap

    # -- training ------------------------------------------------------

    def _kmeans(self, X: np.ndarray, K: int) -> np.ndarray:
        """Lloyd iterations on device over unit vectors (cosine =
        dot). The sample is truncated to a power of two so repeated
        retrains at drifting corpus sizes reuse one compiled step."""
        jax, jnp = self._jax()
        if self._kmeans_fn is None:
            def step(X, C):
                a = jnp.argmax(X @ C.T, axis=1)
                sums = jnp.zeros_like(C).at[a].add(X)
                cnt = jnp.zeros((C.shape[0],), jnp.float32).at[a].add(1.0)
                newc = jnp.where(cnt[:, None] > 0,
                                 sums / jnp.maximum(cnt[:, None], 1.0), C)
                norm = jnp.linalg.norm(newc, axis=1, keepdims=True)
                return newc / jnp.maximum(norm, 1e-30)
            self._kmeans_fn = jax.jit(step)
        m = min(len(X), self.params.train_size)
        m = max(K, 1 << (m.bit_length() - 1))  # pow2 <= m, >= K
        sample = X[:m]
        rng = np.random.default_rng(self.params.seed)
        init = sample[rng.permutation(m)[:K]].astype(np.float32)
        Xd = jax.device_put(sample.astype(np.float32))
        C = jax.device_put(init)
        for _ in range(self.params.kmeans_iters):
            C = self._kmeans_fn(Xd, C)
        return np.asarray(C)

    def _assign_all(self, X: np.ndarray, C: np.ndarray) -> np.ndarray:
        """Nearest-centroid assignment, chunked so one compiled
        program covers any corpus size."""
        jax, jnp = self._jax()
        if self._assign_fn is None:
            def assign(Xc, C):
                return jnp.argmax(Xc @ C.T, axis=1)
            self._assign_fn = jax.jit(assign)
        chunk = 8192
        Cd = jax.device_put(C.astype(np.float32))
        out = np.empty(len(X), dtype=np.int64)
        for lo in range(0, len(X), chunk):
            hi = min(lo + chunk, len(X))
            block = X[lo:hi].astype(np.float32)
            if hi - lo < chunk:  # pad the tail; pad rows are discarded
                block = np.concatenate(
                    [block, np.zeros((chunk - (hi - lo), X.shape[1]),
                                     np.float32)])
            out[lo:hi] = np.asarray(
                self._assign_fn(jax.device_put(block), Cd))[:hi - lo]
        return out

    def rebuild(self, host: np.ndarray, rows: Sequence[int],
                centroids: np.ndarray | None = None) -> None:
        """(Re)train on the live corpus and rebuild every device
        buffer: k-means (or the given centroids — the persistence
        path), full reassignment, allocator placement, spill fold."""
        rows = np.asarray(list(rows), dtype=np.int64)
        n = len(rows)
        if n == 0:
            self.trained = False
            self._locator.clear()
            self._d_centroids = self._d_rowids = self._d_spill = None
            self._spill_n = self._spill_live = self._indexed_live = 0
            return
        X = host[rows].astype(np.float32)
        if centroids is None:
            K = self.auto_nlist(n)
            K = min(K, n)
            centroids = self._kmeans(X, K)
        else:
            centroids = np.asarray(centroids, dtype=np.float32)
        K = centroids.shape[0]
        assign = self._assign_all(X, centroids)
        sizes = np.bincount(assign, minlength=K)
        mean = max(1, n // K)
        cap = max(8, int(self.params.pad_factor * mean))
        pad = next_pow2(min(int(sizes.max()) if n else 1, cap))
        alloc = ListShardAllocator(self.num_shards, K)
        slot_of_list = alloc.assign(sizes)
        sps = alloc.slots_per_shard
        lp = self.num_shards * sps
        rowids_np = np.full((lp, pad), -1, dtype=np.int32)
        cents_np = np.zeros((lp, self.dim), dtype=np.float32)
        cents_np[slot_of_list] = centroids
        self._locator.clear()
        fill = np.zeros(K, dtype=np.int64)
        spill_rows: list[int] = []
        for i in range(n):
            l = int(assign[i])
            r = int(rows[i])
            c = int(fill[l])
            if c < pad:
                slot = int(slot_of_list[l])
                rowids_np[slot, c] = r
                self._locator[r] = ("l", slot, c)
                fill[l] = c + 1
            else:  # imbalance overflow: exact-scored via the spill
                spill_rows.append(r)
        self.overflow_count = len(spill_rows)
        self._indexed_live = n - len(spill_rows)
        self.nlist, self.pad, self.sps = K, pad, sps
        self.centroids_np = centroids
        self._d_centroids = self._put(cents_np, ("dp", None))
        self._d_rowids = self._put(rowids_np, ("dp", None))
        self._rebuild_spill(spill_rows)
        self.trained = True
        self.trained_at_n = n
        self.generation += 1

    def _rebuild_spill(self, spill_rows: list[int]) -> None:
        per_shard = next_pow2(max(
            64, math.ceil(2 * max(1, len(spill_rows)) / self.num_shards)))
        cap = self.num_shards * per_shard
        arr = np.full(cap, -1, dtype=np.int32)
        for pos, r in enumerate(spill_rows):
            arr[pos] = r
            self._locator[r] = ("s", pos)
        self._d_spill = self._put(arr, ("dp",))
        self._spill_n = len(spill_rows)
        self._spill_live = len(spill_rows)

    # -- incremental maintenance --------------------------------------

    def _patches(self):
        jax, jnp = self._jax()
        if self._patch1d_fn is None:
            def patch1d(buf, pos, vals):
                return buf.at[pos].set(vals)

            def patch2d(buf, slots, offs, vals):
                return buf.at[slots, offs].set(vals)
            self._patch1d_fn = jax.jit(patch1d, donate_argnums=(0,))
            self._patch2d_fn = jax.jit(patch2d, donate_argnums=(0,))
        return self._patch1d_fn, self._patch2d_fn

    @staticmethod
    def _bucket(arrs: list[np.ndarray]) -> list[np.ndarray]:
        """Pad index/value arrays to a power-of-two length (repeating
        the first entry — scatter-set with duplicate targets writing
        the same value is idempotent) so patch program shapes stay a
        bounded set."""
        n = len(arrs[0])
        b = next_pow2(n)
        return [np.concatenate([a, np.repeat(a[:1], b - n)]) if b > n
                else a for a in arrs]

    def add(self, rows: Sequence[int]) -> None:
        """Append freshly-ingested rows to the spill block (never
        blocks on a rebuild — the fold happens at the next retrain)."""
        rows = [int(r) for r in rows if int(r) not in self._locator]
        if not rows or not self.trained:
            return
        _, jnp = self._jax()
        cap = int(self._d_spill.shape[0])
        if self._spill_n + len(rows) > cap:
            # grow + compact (drops -1 holes left by removals)
            live = [r for r, loc in self._locator.items()
                    if loc[0] == "s"]
            for r in live:
                del self._locator[r]
            self._rebuild_spill(live + rows)  # counts _spill_live itself
            return
        patch1d, _ = self._patches()
        pos = np.arange(self._spill_n, self._spill_n + len(rows),
                        dtype=np.int32)
        vals = np.asarray(rows, dtype=np.int32)
        pos, vals = self._bucket([pos, vals])
        self._d_spill = patch1d(self._d_spill, jnp.asarray(pos),
                                jnp.asarray(vals))
        for i, r in enumerate(rows):
            self._locator[r] = ("s", self._spill_n + i)
        self._spill_n += len(rows)
        self._spill_live += len(rows)

    def remove(self, rows: Sequence[int]) -> None:
        """Drop rows from their posting-list / spill slots (one
        stacked donated patch per buffer, not one dispatch per row)."""
        if not self.trained:
            return
        _, jnp = self._jax()
        slots, offs, spos = [], [], []
        for r in rows:
            loc = self._locator.pop(int(r), None)
            if loc is None:
                continue
            if loc[0] == "l":
                slots.append(loc[1])
                offs.append(loc[2])
                self._indexed_live -= 1
            else:
                spos.append(loc[1])
                self._spill_live -= 1
        patch1d, patch2d = self._patches()
        if slots:
            s, o = self._bucket([np.asarray(slots, np.int32),
                                 np.asarray(offs, np.int32)])
            vals = np.full(len(s), -1, dtype=np.int32)
            self._d_rowids = patch2d(self._d_rowids, jnp.asarray(s),
                                     jnp.asarray(o), jnp.asarray(vals))
        if spos:
            (p,) = self._bucket([np.asarray(spos, np.int32)])
            vals = np.full(len(p), -1, dtype=np.int32)
            self._d_spill = patch1d(self._d_spill, jnp.asarray(p),
                                    jnp.asarray(vals))

    # -- search --------------------------------------------------------

    @staticmethod
    def _search_body(matrix, cents, rowids, spill, q, *, nprobe, k):
        """ONE shard's fused search: centroid scores → top-nprobe
        local lists → candidate gather → exact rescore against the
        live matrix → shard-local top-k. Queries rescore in groups of
        ``_RESCORE_GROUP`` (lax.map batch_size) so the candidate
        working set stays [G, C, dim], not [B, C, dim] — G vectorizes
        enough to amortize dispatch (the batched-QPS half of the
        tentpole) without materializing the full batch's candidates."""
        import jax
        import jax.numpy as jnp
        b = q.shape[0]
        pad = rowids.shape[1]
        cs = q @ cents.T                          # [B, lists_local]
        _, pl = jax.lax.top_k(cs, nprobe)         # [B, nprobe]
        cand = rowids[pl].reshape(b, nprobe * pad)
        cand = jnp.concatenate(
            [cand, jnp.broadcast_to(spill[None, :],
                                    (b, spill.shape[0]))], axis=1)

        def per_query(args):
            qv, c = args
            safe = jnp.clip(c, 0, matrix.shape[0] - 1)
            vecs = matrix[safe]                   # [C, dim] gather
            s = (vecs @ qv.astype(matrix.dtype)).astype(jnp.float32)
            s = jnp.where(c >= 0, s, jnp.float32("-inf"))
            v, i = jax.lax.top_k(s, k)
            return v, jnp.take(c, i)

        return jax.lax.map(per_query, (q, cand),
                           batch_size=min(b, _RESCORE_GROUP))

    def _search_dispatch(self):
        jax, _ = self._jax()
        if self._search_fn is not None:
            return self._search_fn
        if self.mesh is None:
            self._search_fn = jax.jit(self._search_body,
                                      static_argnames=("nprobe", "k"))
        else:
            import functools

            try:  # jax >= 0.5
                from jax import shard_map
            except ImportError:  # this toolchain
                from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            mesh = self.mesh

            def mesh_search(matrix, cents, rowids, spill, q, *,
                            nprobe, k):
                body = functools.partial(self._search_body,
                                         nprobe=nprobe, k=k)
                sm = shard_map(
                    body, mesh,
                    in_specs=(P(None, None), P("dp", None),
                              P("dp", None), P("dp"), P(None, None)),
                    out_specs=(P(None, "dp"), P(None, "dp")),
                    check_rep=False)
                return sm(matrix, cents, rowids, spill, q)

            self._search_fn = jax.jit(mesh_search,
                                      static_argnames=("nprobe", "k"))
        return self._search_fn

    def search(self, matrix, qs: np.ndarray, k: int,
               nprobe: int | None = None):
        """Search B queries; returns host arrays ``(vals, rows)`` of
        shape ``[B, shards*k]``, merged (host cross-shard top-k
        reduction = one argsort over shards*k rows per query) and a
        stats dict. ``rows`` may contain -1 (score -inf) when fewer
        than k live candidates were reachable."""
        _, jnp = self._jax()
        npb = min(nprobe if nprobe is not None else self.params.nprobe,
                  self.sps)
        spill_local = int(self._d_spill.shape[0]) // self.num_shards
        k_eff = min(int(k), npb * self.pad + spill_local)
        b = len(qs)
        bp = next_pow2(b)
        if bp > b:  # bucket B so program count stays bounded
            qs = np.concatenate(
                [qs, np.zeros((bp - b, qs.shape[1]), qs.dtype)])
        fn = self._search_dispatch()
        vals, rows = fn(matrix, self._d_centroids, self._d_rowids,
                        self._d_spill, jnp.asarray(qs, jnp.float32),
                        nprobe=npb, k=k_eff)
        vals = np.asarray(vals)[:b]
        rows = np.asarray(rows)[:b]
        order = np.argsort(-vals, axis=1, kind="stable")
        vals = np.take_along_axis(vals, order, axis=1)
        rows = np.take_along_axis(rows, order, axis=1)
        lists_scanned = min(npb * self.num_shards, self.nlist)
        stats = {
            "nprobe": npb,
            "lists_scanned": lists_scanned,
            "lists_scanned_frac": (lists_scanned / self.nlist
                                   if self.nlist else 0.0),
            "spill_fraction": round(self.spill_frac(), 4),
            "k": k_eff,
        }
        return vals, rows, stats
