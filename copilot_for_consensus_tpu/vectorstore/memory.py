"""NumPy exact-search vector store (tests and small corpora).

Keeps vectors L2-normalized in a contiguous matrix so query() is a single
matvec + argpartition — the same math the TPU driver runs on-device.
"""

from __future__ import annotations

import json
import pathlib
import threading
from typing import Any, Mapping, Sequence

import numpy as np

from copilot_for_consensus_tpu.storage.base import matches_filter
from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)


class InMemoryVectorStore(VectorStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self._dim: int | None = cfg.get("dimension") or None
        self._ids: list[str] = []
        self._index: dict[str, int] = {}
        self._vectors = np.zeros((0, self._dim or 1), dtype=np.float32)
        self._metadata: list[dict[str, Any]] = []
        self._lock = threading.RLock()
        self.persist_path = cfg.get("persist_path")

    @property
    def dimension(self) -> int | None:
        return self._dim

    @staticmethod
    def _normalize(vector: Sequence[float]) -> np.ndarray:
        arr = np.asarray(vector, dtype=np.float32)
        norm = float(np.linalg.norm(arr))
        return arr / norm if norm > 0 else arr

    def add_embedding(self, vec_id, vector, metadata=None):
        with self._lock:
            arr = self._normalize(vector)
            if self._dim is None:
                self._dim = arr.shape[0]
                self._vectors = np.zeros((0, self._dim), dtype=np.float32)
            if arr.shape[0] != self._dim:
                raise VectorStoreError(
                    f"dimension mismatch: store={self._dim} got={arr.shape[0]}")
            if vec_id in self._index:  # upsert
                row = self._index[vec_id]
                self._vectors[row] = arr
                self._metadata[row] = dict(metadata or {})
            else:
                self._index[vec_id] = len(self._ids)
                self._ids.append(vec_id)
                self._vectors = np.vstack([self._vectors, arr[None, :]])
                self._metadata.append(dict(metadata or {}))

    def query(self, vector, top_k=10, flt=None):
        with self._lock:
            if not self._ids:
                return []
            q = self._normalize(vector)
            scores = self._vectors @ q
            if flt:
                mask = np.array(
                    [matches_filter(m, flt) for m in self._metadata])
                scores = np.where(mask, scores, -np.inf)
            k = min(top_k, len(self._ids))
            top = np.argpartition(-scores, k - 1)[:k]
            top = top[np.argsort(-scores[top])]
            return [
                QueryResult(self._ids[i], float(scores[i]),
                            dict(self._metadata[i]))
                for i in top if np.isfinite(scores[i])
            ]

    def get(self, vec_id):
        with self._lock:
            row = self._index.get(vec_id)
            if row is None:
                return None
            return self._vectors[row].tolist(), dict(self._metadata[row])

    def delete_by_filter(self, flt):
        with self._lock:
            doomed = [vid for vid, row in self._index.items()
                      if matches_filter(self._metadata[row], flt)]
        return self.delete(doomed)

    def delete(self, vec_ids):
        with self._lock:
            keep = [i for i, vid in enumerate(self._ids)
                    if vid not in set(vec_ids)]
            removed = len(self._ids) - len(keep)
            self._ids = [self._ids[i] for i in keep]
            self._vectors = self._vectors[keep] if keep else np.zeros(
                (0, self._dim or 1), dtype=np.float32)
            self._metadata = [self._metadata[i] for i in keep]
            self._index = {vid: i for i, vid in enumerate(self._ids)}
            return removed

    def count(self):
        with self._lock:
            return len(self._ids)

    def clear(self):
        with self._lock:
            self._ids = []
            self._index = {}
            self._vectors = np.zeros((0, self._dim or 1), dtype=np.float32)
            self._metadata = []

    # -- persistence -------------------------------------------------------

    def save(self, path: str | pathlib.Path | None = None) -> None:
        path = pathlib.Path(path or self.persist_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            np.savez_compressed(
                path, vectors=self._vectors,
                ids=np.array(self._ids, dtype=object),
                metadata=np.array(
                    [json.dumps(m) for m in self._metadata], dtype=object),
            )

    def load(self, path: str | pathlib.Path | None = None) -> None:
        path = pathlib.Path(path or self.persist_path)
        data = np.load(path, allow_pickle=True)
        with self._lock:
            self._vectors = data["vectors"].astype(np.float32)
            self._ids = [str(x) for x in data["ids"]]
            self._metadata = [json.loads(str(m)) for m in data["metadata"]]
            self._index = {vid: i for i, vid in enumerate(self._ids)}
            self._dim = self._vectors.shape[1] if len(self._ids) else None
