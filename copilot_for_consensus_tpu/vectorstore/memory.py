"""NumPy exact-search vector store (tests and small corpora).

Keeps vectors L2-normalized in a contiguous matrix so query() is a single
matvec + argpartition — the same math the TPU driver runs on-device.
Scalar-equality metadata filters hit an inverted index (same design as
the TPU driver), so per-thread context queries are O(candidates), not
O(corpus); the vector buffer grows geometrically so adds are amortized
O(1) instead of a full copy each.
"""

from __future__ import annotations

import json
import pathlib
import threading
from typing import Any, Sequence

import numpy as np

from copilot_for_consensus_tpu.storage.base import matches_filter
from copilot_for_consensus_tpu.vectorstore._inverted import InvertedIndexMixin
from copilot_for_consensus_tpu.vectorstore.base import (
    QueryResult,
    VectorStore,
    VectorStoreError,
)


class InMemoryVectorStore(InvertedIndexMixin, VectorStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self._dim: int | None = cfg.get("dimension") or None
        self._ids: list[str] = []
        self._index: dict[str, int] = {}
        self._vectors = np.zeros((0, self._dim or 1), dtype=np.float32)
        self._metadata: list[dict[str, Any]] = []
        self._init_inverted()
        self._lock = threading.RLock()
        self.persist_path = cfg.get("persist_path")

    @property
    def dimension(self) -> int | None:
        with self._lock:
            return self._dim

    @staticmethod
    def _normalize(vector: Sequence[float]) -> np.ndarray:
        arr = np.asarray(vector, dtype=np.float32)
        norm = float(np.linalg.norm(arr))
        return arr / norm if norm > 0 else arr

    @property
    def _n(self) -> int:
        with self._lock:
            return len(self._ids)

    def _grow_to(self, rows: int) -> None:
        cap = self._vectors.shape[0]
        if rows <= cap:
            return
        new_cap = max(rows, cap * 2, 64)
        grown = np.zeros((new_cap, self._dim), dtype=np.float32)
        grown[:self._n] = self._vectors[:self._n]
        self._vectors = grown

    def add_embedding(self, vec_id, vector, metadata=None):
        with self._lock:
            arr = self._normalize(vector)
            if self._dim is None:
                self._dim = arr.shape[0]
                self._vectors = np.zeros((0, self._dim), dtype=np.float32)
            if arr.shape[0] != self._dim:
                raise VectorStoreError(
                    f"dimension mismatch: store={self._dim} got={arr.shape[0]}")
            meta = dict(metadata or {})
            if vec_id in self._index:  # upsert
                row = self._index[vec_id]
                self._vectors[row] = arr
                self._index_meta(row, meta, remove=self._metadata[row])
                self._metadata[row] = meta
            else:
                row = self._n
                self._grow_to(row + 1)
                self._index[vec_id] = row
                self._ids.append(vec_id)
                self._vectors[row] = arr
                self._metadata.append(meta)
                self._index_meta(row, meta)

    def query(self, vector, top_k=10, flt=None):
        with self._lock:
            if not self._ids:
                return []
            q = self._normalize(vector)
            if flt:
                cand = self._matching_rows(flt)
                if not cand:
                    return []
                idx = np.asarray(cand)
                scores = self._vectors[idx] @ q
                k = min(top_k, len(cand))
                top = np.argpartition(-scores, k - 1)[:k]
                top = top[np.argsort(-scores[top])]
                return [QueryResult(self._ids[idx[i]], float(scores[i]),
                                    dict(self._metadata[idx[i]]))
                        for i in top]
            scores = self._vectors[:self._n] @ q
            k = min(top_k, self._n)
            top = np.argpartition(-scores, k - 1)[:k]
            top = top[np.argsort(-scores[top])]
            return [
                QueryResult(self._ids[i], float(scores[i]),
                            dict(self._metadata[i]))
                for i in top
            ]

    def _matching_rows(self, flt) -> list[int]:
        """Rows whose metadata matches ``flt``: index candidates
        re-verified with matches_filter (the index is a superset guess),
        or a full scan when the index can't decide the filter."""
        cand = self._filter_candidates(flt)
        if cand is None:
            return [i for i, m in enumerate(self._metadata)
                    if matches_filter(m, flt)]
        return [i for i in sorted(cand)
                if matches_filter(self._metadata[i], flt)]

    def get(self, vec_id):
        with self._lock:
            row = self._index.get(vec_id)
            if row is None:
                return None
            return self._vectors[row].tolist(), dict(self._metadata[row])

    def delete_by_filter(self, flt):
        with self._lock:
            doomed = [vid for vid, row in self._index.items()
                      if matches_filter(self._metadata[row], flt)]
        return self.delete(doomed)

    def delete(self, vec_ids):
        doomed = set(vec_ids)
        with self._lock:
            keep = [i for i, vid in enumerate(self._ids)
                    if vid not in doomed]
            removed = self._n - len(keep)
            self._ids = [self._ids[i] for i in keep]
            self._vectors = (self._vectors[keep] if keep
                             else np.zeros((0, self._dim or 1),
                                           dtype=np.float32))
            self._metadata = [self._metadata[i] for i in keep]
            self._index = {vid: i for i, vid in enumerate(self._ids)}
            self._init_inverted()
            for row, meta in enumerate(self._metadata):
                self._index_meta(row, meta)
            return removed

    def count(self):
        with self._lock:
            return self._n

    def clear(self):
        with self._lock:
            self._ids = []
            self._index = {}
            self._vectors = np.zeros((0, self._dim or 1), dtype=np.float32)
            self._metadata = []
            self._init_inverted()

    # -- persistence -------------------------------------------------------

    def save(self, path: str | pathlib.Path | None = None) -> None:
        path = pathlib.Path(path or self.persist_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with self._lock:
            np.savez_compressed(
                path, vectors=self._vectors[:self._n],
                ids=np.array(self._ids, dtype=object),
                metadata=np.array(
                    [json.dumps(m) for m in self._metadata], dtype=object),
            )

    def load(self, path: str | pathlib.Path | None = None) -> None:
        path = pathlib.Path(path or self.persist_path)
        data = np.load(path, allow_pickle=True)
        with self._lock:
            self._vectors = data["vectors"].astype(np.float32)
            self._ids = [str(x) for x in data["ids"]]
            self._metadata = [json.loads(str(m)) for m in data["metadata"]]
            self._index = {vid: i for i, vid in enumerate(self._ids)}
            self._dim = self._vectors.shape[1] if len(self._ids) else None
            self._init_inverted()
            for row, meta in enumerate(self._metadata):
                self._index_meta(row, meta)
