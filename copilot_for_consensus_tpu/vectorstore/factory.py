"""Vector store driver registration + create_vector_store."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.vectorstore.memory import InMemoryVectorStore


def create_vector_store(config: Any = None):
    cfg = dict(config or {})
    driver = cfg.get("driver", "memory")
    if driver == "memory":
        return InMemoryVectorStore(cfg)
    if driver == "tpu":
        from copilot_for_consensus_tpu.vectorstore.tpu import TPUVectorStore

        return TPUVectorStore(cfg)
    if driver == "native":
        from copilot_for_consensus_tpu.vectorstore.native import NativeFlatVectorStore

        return NativeFlatVectorStore(cfg)
    if driver == "azure_ai_search":
        from copilot_for_consensus_tpu.vectorstore.azure_ai_search import (
            AzureAISearchVectorStore,
        )

        return AzureAISearchVectorStore(cfg)
    raise ValueError(f"unknown vector_store driver {driver!r}")


for _name in ("memory", "tpu", "native", "azure_ai_search"):
    register_driver("vector_store", _name, create_vector_store)
