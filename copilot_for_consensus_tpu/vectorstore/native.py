"""Native host-side exact-search driver: C++ scoring/top-k via ctypes.

The first-party replacement for the FAISS role in the reference
(``faiss_store.py:18`` — a C++ flat index consumed as a library). The
store layer (ids, metadata, inverted-index filters, persistence) is
shared with :class:`InMemoryVectorStore`; only the hot loop — dot-product
scoring + top-k selection over the packed matrix — runs in C++
(``_native/topk.cpp``), compiled on first use with g++ into a cached
shared object. No compiler → transparent NumPy fallback, same results.

Use this driver for host-resident corpora when a TPU is absent or busy;
the ``tpu`` driver keeps the corpus in HBM and scores on-device.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
import tempfile
import threading
from typing import Any

import numpy as np

from copilot_for_consensus_tpu.vectorstore.base import QueryResult
from copilot_for_consensus_tpu.vectorstore.memory import InMemoryVectorStore

_SRC = pathlib.Path(__file__).resolve().parent / "_native" / "topk.cpp"
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None | bool = None   # None = not tried, False = failed


# Module-level override for the compiled-object cache dir (tests, build
# farms); default is the system tempdir. Not config-driven: this is
# toolchain plumbing, and the no-runtime-env-vars policy
# (tests/test_no_runtime_env_vars.py) bans env reads here.
BUILD_CACHE_DIR: str | None = None


def _build_dir() -> pathlib.Path:
    return pathlib.Path(BUILD_CACHE_DIR or os.path.join(
        tempfile.gettempdir(), "copilot-native"))


def load_native_lib() -> ctypes.CDLL | None:
    """Compile (once, cached by source hash) and load the C++ core.
    Returns None when no toolchain is available."""
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB or None
        try:
            src = _SRC.read_bytes()
            tag = hashlib.sha256(src).hexdigest()[:16]
            out = _build_dir() / f"topk-{tag}.so"
            if not out.exists():
                out.parent.mkdir(parents=True, exist_ok=True)
                tmp = out.with_suffix(f".build-{os.getpid()}.so")
                # NEVER -ffast-math here: it links crtfastmath.o into
                # the .so, and loading that flips FTZ/DAZ process-wide
                # (see topk.cpp header).
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC",
                     "-std=c++17", str(_SRC), "-o", str(tmp)],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, out)   # atomic vs concurrent builders
            lib = ctypes.CDLL(str(out))
            i64 = ctypes.c_int64
            fp = ctypes.POINTER(ctypes.c_float)
            ip = ctypes.POINTER(i64)
            lib.topk_dot.argtypes = [fp, i64, i64, fp, ip, i64, i64,
                                     ip, fp]
            lib.topk_dot.restype = None
            _LIB = lib
        except Exception:
            _LIB = False
        return _LIB or None


class NativeFlatVectorStore(InMemoryVectorStore):
    """InMemoryVectorStore with the scoring/top-k hot loop in C++."""

    def __init__(self, config: Any = None):
        super().__init__(config)
        self._lib = load_native_lib()

    @property
    def native_available(self) -> bool:
        return self._lib is not None

    def _native_topk(self, q: np.ndarray, rows: np.ndarray | None,
                     k: int) -> tuple[np.ndarray, np.ndarray]:
        n = self._n
        vecs = np.ascontiguousarray(self._vectors[:n])
        q = np.ascontiguousarray(q, dtype=np.float32)
        total = n if rows is None else len(rows)
        k = min(k, total)
        out_idx = np.zeros(k, dtype=np.int64)
        out_score = np.zeros(k, dtype=np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        ip = ctypes.POINTER(ctypes.c_int64)
        rows_ptr = (None if rows is None else
                    np.ascontiguousarray(rows, dtype=np.int64))
        self._lib.topk_dot(
            vecs.ctypes.data_as(fp), n, vecs.shape[1],
            q.ctypes.data_as(fp),
            rows_ptr.ctypes.data_as(ip) if rows_ptr is not None else None,
            0 if rows_ptr is None else len(rows_ptr),
            k, out_idx.ctypes.data_as(ip),
            out_score.ctypes.data_as(fp))
        return out_idx[:k], out_score[:k]

    def query(self, vector, top_k=10, flt=None):
        if self._lib is None:
            return super().query(vector, top_k, flt)
        with self._lock:
            if not self._ids:
                return []
            q = self._normalize(vector)
            rows = None
            if flt:
                cand = self._matching_rows(flt)
                if not cand:
                    return []
                rows = np.asarray(cand, dtype=np.int64)
            idx, scores = self._native_topk(q, rows, top_k)
            return [QueryResult(self._ids[i], float(s),
                                dict(self._metadata[i]))
                    for i, s in zip(idx, scores)]
