// Host-side exact-search core: dot-product scoring + top-k selection.
//
// The role FAISS's C++ core plays for the reference
// (adapters/copilot_vectorstore/copilot_vectorstore/faiss_store.py:18,
// IndexFlatL2 at :101) — first-party, C ABI only (loaded via ctypes; no
// pybind11 in the image). Vectors are L2-normalized by the Python layer,
// so dot == cosine. Selection is a bounded min-heap, O(n log k).
//
// NO -ffast-math: gcc links crtfastmath.o into shared objects built with
// it, and dlopen'ing that sets FTZ/DAZ in MXCSR for the WHOLE process —
// silently breaking subnormals for the embedding JAX code (and anything
// else) in the host. The dot product instead uses 4 independent
// accumulators so -O3 can vectorize the reduction under strict IEEE
// ordering.
//
// Build: compiled on demand by vectorstore/native.py with g++ into a
// cached shared object; the Python driver falls back to NumPy when no
// compiler is available.

#include <cstdint>
#include <vector>
#include <algorithm>

extern "C" {

static inline float dot(const float* row, const float* q, int64_t dim) {
    float a0 = 0.0f, a1 = 0.0f, a2 = 0.0f, a3 = 0.0f;
    int64_t j = 0;
    for (; j + 4 <= dim; j += 4) {
        a0 += row[j] * q[j];
        a1 += row[j + 1] * q[j + 1];
        a2 += row[j + 2] * q[j + 2];
        a3 += row[j + 3] * q[j + 3];
    }
    for (; j < dim; ++j) a0 += row[j] * q[j];
    return (a0 + a1) + (a2 + a3);
}

// scores[i] = dot(vecs[i], q); vecs is row-major [n, dim].
void dot_scores(const float* vecs, int64_t n, int64_t dim,
                const float* q, float* scores) {
    for (int64_t i = 0; i < n; ++i)
        scores[i] = dot(vecs + i * dim, q, dim);
}

// Top-k by score over rows[0..n): writes k (idx, score) pairs sorted
// descending. rows==nullptr means identity (all n rows).
void topk_dot(const float* vecs, int64_t n, int64_t dim,
              const float* q, const int64_t* rows, int64_t n_rows,
              int64_t k, int64_t* out_idx, float* out_score) {
    const int64_t total = rows ? n_rows : n;
    if (k > total) k = total;
    if (k <= 0) return;
    using Pair = std::pair<float, int64_t>;  // (score, row)
    std::vector<Pair> heap;                  // min-heap of the best k
    heap.reserve(k);
    auto cmp = [](const Pair& a, const Pair& b) { return a.first > b.first; };
    for (int64_t t = 0; t < total; ++t) {
        const int64_t i = rows ? rows[t] : t;
        const float acc = dot(vecs + i * dim, q, dim);
        if ((int64_t)heap.size() < k) {
            heap.emplace_back(acc, i);
            std::push_heap(heap.begin(), heap.end(), cmp);
        } else if (acc > heap.front().first) {
            std::pop_heap(heap.begin(), heap.end(), cmp);
            heap.back() = Pair(acc, i);
            std::push_heap(heap.begin(), heap.end(), cmp);
        }
    }
    // sort_heap on a greater-comparator min-heap leaves descending score.
    std::sort_heap(heap.begin(), heap.end(), cmp);
    for (int64_t t = 0; t < k; ++t) {
        out_idx[t] = heap[t].second;
        out_score[t] = heap[t].first;
    }
}

}  // extern "C"
