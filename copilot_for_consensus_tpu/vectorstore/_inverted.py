"""Shared inverted index for metadata-equality filters.

One implementation, one semantics, for every host-resident driver
(memory/native/tpu): scalar (str/int/bool) top-level metadata values
index into (key, value) → row sets. ``filter_candidates`` answers a
filter ONLY when the index can decide it soundly — any dotted-path key,
any key that ever carried an unindexable value, or any non-scalar filter
condition returns None so the caller falls back to the full
``matches_filter`` scan. Candidates are a SUPERSET guess (int/bool/float
hash-equality blurs 1/True/1.0): callers must re-verify each candidate
with ``matches_filter`` before returning it.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Mapping


class InvertedIndexMixin:
    _inverted: dict[tuple[str, Any], set[int]]
    _unindexed_keys: set[str]

    def _init_inverted(self) -> None:
        self._inverted = defaultdict(set)
        self._unindexed_keys = set()

    def _index_meta(self, row: int, meta: Mapping[str, Any],
                    remove: Mapping[str, Any] | None = None) -> None:
        for k, v in (remove or {}).items():
            if isinstance(v, (str, int, bool)):
                self._inverted.get((k, v), set()).discard(row)
        for k, v in meta.items():
            if isinstance(v, (str, int, bool)):
                self._inverted[(k, v)].add(row)
            else:
                # This key is no longer fully covered by the index; any
                # filter on it must scan (a miss would otherwise read as
                # authoritative "no matches").
                self._unindexed_keys.add(k)

    def _filter_candidates(self, flt: Mapping[str, Any]) -> set[int] | None:
        """Candidate row superset for ``flt`` via the index, or None when
        the index cannot decide the filter soundly."""
        sets = []
        for k, v in flt.items():
            if ("." in k or k.startswith("$")
                    or k in self._unindexed_keys
                    or not isinstance(v, (str, int, bool))):
                return None
            sets.append(self._inverted.get((k, v), set()))
        if not sets:
            return None
        return set.intersection(*sets) if len(sets) > 1 else set(sets[0])
