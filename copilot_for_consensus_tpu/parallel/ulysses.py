"""Ulysses sequence parallelism: all-to-all head↔sequence resharding.

The alternative to ring attention (SURVEY.md §2.3 "Ring attention /
Ulysses") for long-context forwards: instead of rotating KV blocks
around the ring (n-1 ppermute hops), ONE all-to-all reshards q/k/v from
sequence-sharded [B, H, S/n, D] to head-sharded [B, H/n, S, D], each
device runs ordinary full-sequence attention over its head group, and a
second all-to-all reshards back. Preferable when n is large (2 ICI
collectives instead of n-1 hops) and H is divisible by the axis; ring
wins when heads are scarce or memory for the full-S KV per device is
tight — which is why both ship.

Same drop-in ``attn_impl`` contract as ``parallel.ring.ring_attention``;
oracle-tested against ``attention_xla`` on the virtual mesh.
"""

from __future__ import annotations

import functools

import jax
try:
    from jax import shard_map
except ImportError:   # jax < 0.5 exports it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from copilot_for_consensus_tpu.analysis.contracts import checkable
from copilot_for_consensus_tpu.ops.attention import attention_xla


def _ulysses_shard(q, k, v, kv_lengths, *, axis_name: str, causal: bool,
                   window: int):
    """Per-shard body. q/k/v: [B, H, S_loc, D] → attention over the full
    sequence for H/n of the heads, resharded back."""
    # seq-sharded → head-sharded: split heads (axis 1) across the mesh
    # axis, concatenate the gathered sequence blocks (axis 2).
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    # Full sequence is local now: the standard masked attention applies
    # (global positions are just 0..S-1).
    out = attention_xla(qh, kh, vh, causal=causal, window=window,
                        kv_lengths=kv_lengths)
    return to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    window: int = 0,
    kv_lengths=None,
    impl: str | None = None,     # accepted for attention-impl interface
) -> jax.Array:
    """Drop-in attention impl (same [B, H, S, D] contract as
    ``ops.attention.attention``) with the sequence axis sharded over
    ``axis``. Heads must divide by the axis size; GQA kv heads are
    expanded first (head groups must align across q/k/v for the
    all-to-all to pair them)."""
    from copilot_for_consensus_tpu.ops.attention import _gqa_expand

    hq = q.shape[1]
    k = _gqa_expand(k, hq)
    v = _gqa_expand(v, hq)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(
            f"sequence {q.shape[2]} not divisible by {axis}={n}")
    if hq % n:
        raise ValueError(
            f"heads {hq} not divisible by {axis}={n}; use ring attention")
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ulysses_shard, axis_name=axis, causal=causal,
                          window=int(window)),
        mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec,
    )
    return fn(q, k, v, kv_lengths)


def make_ulysses_attention(mesh: Mesh, axis: str = "sp"):
    """Bind mesh/axis → a callable usable as ``attn_impl`` in the model
    forward passes, interchangeable with ``make_ring_attention``."""
    return functools.partial(ulysses_attention, mesh=mesh, axis=axis)


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("ulysses-attention")
def _shardcheck_ulysses_attention():
    """Trace the double all-to-all under the real sp mesh with the
    module's DEFAULT axis binding: the all_to_all collectives in
    ``_ulysses_shard`` must name an axis the mesh has, heads must
    divide by it (the head↔sequence reshard pairs head groups across
    ranks), and the sequence must divide for the seq-sharded specs."""
    import jax.numpy as jnp

    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase,
        require_devices,
    )
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(sp=4), devices=jax.devices()[:8])
    S = jax.ShapeDtypeStruct
    b, hq, hkv, s, d = 1, 8, 4, 256, 64
    q = S((b, hq, s, d), jnp.bfloat16)
    kv = S((b, hkv, s, d), jnp.bfloat16)
    return ContractCase(
        fn=functools.partial(ulysses_attention, mesh=mesh),
        args=(q, kv, kv),
        kwargs={"kv_lengths": S((b,), jnp.int32)},
        mesh=mesh,
    )
