"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Blockwise attention with online-softmax accumulation while KV blocks
rotate around the ring via ``ppermute`` (one ICI hop per step, compute
overlapping communication at the XLA level). The sequence axis of q/k/v
is sharded over ``sp``; each device holds S/n query positions and visits
every KV block after n-1 rotations.

This is a NEW capability relative to the reference, which avoids long
context by top-k truncation to a 3000-token budget
(``orchestrator/app/context_selectors.py:94-107``; SURVEY.md §5
"Long-context / sequence parallelism: Absent"). With CP, whole
threads/archives fit in context instead of being truncated — the
BASELINE.json v5p "long multi-thread consensus" configuration.

Numerics: identical accumulation scheme to the flash kernel
(``ops/flash_attention.py``); oracle-tested against ``attention_xla`` on
the virtual mesh in ``tests/test_parallel_ring.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:   # jax < 0.5 exports it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from copilot_for_consensus_tpu.analysis.contracts import checkable

NEG_INF = -1e30


def _ring_shard(q, k, v, kv_lengths, *, axis_name: str, causal: bool,
                scale: float, window: int):
    """Per-shard body. q/k/v: [B, H, S_loc, D] (this shard's blocks);
    kv_lengths: [B] valid-length mask (replicated), or None."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    qf = q.astype(jnp.float32)
    q_pos = idx * s_loc + jnp.arange(s_loc)              # global positions

    # pcast: constants are "unvarying" over the mesh axis; the loop carry
    # becomes varying after the first ppermute, so types must match.
    if hasattr(jax.lax, "pcast"):
        vary = lambda x: jax.lax.pcast(
            x, (axis_name,), to="varying")  # noqa: E731
    else:   # jax < 0.7: no varying/unvarying type system
        vary = lambda x: x  # noqa: E731
    m0 = vary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    acc0 = vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        m, l, acc, k_blk, v_blk = carry
        # After i rotations we hold the kv block originally on shard
        # (idx - i) mod n.
        src = (idx - i) % n
        k_pos = src * s_loc + jnp.arange(s_loc)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        # Build the [B?, s_loc, s_loc] validity mask exactly as the
        # non-ring paths do (ops.attention.make_attention_mask), with
        # k_pos expressed in global coordinates so rotation is invisible.
        mask = jnp.ones((s_loc, s_loc), dtype=bool)
        if causal:
            mask &= k_pos[None, :] <= q_pos[:, None]
        if window > 0:
            mask &= k_pos[None, :] > q_pos[:, None] - window
        mask = mask[None, None]                          # [1, 1, q, k]
        if kv_lengths is not None:
            mask = mask & (k_pos[None, None, None, :]
                           < kv_lengths[:, None, None, None])
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = corr * acc + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return m_new, l, acc, k_blk, v_blk

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, acc0, k, v))
    safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    window: int = 0,
    kv_lengths=None,
    impl: str | None = None,     # accepted for attention-impl interface
) -> jax.Array:
    """Drop-in attention impl (same [B, H, S, D] contract as
    ``ops.attention.attention``) with the sequence axis sharded over
    ``axis``. GQA kv heads are expanded before sharding (kv replication
    across the ring would defeat the rotation). ``window`` applies
    Mistral-style sliding-window masking and ``kv_lengths`` masks padded
    kv positions — both in global coordinates, matching
    ``ops.attention.make_attention_mask``."""
    from copilot_for_consensus_tpu.ops.attention import _gqa_expand

    hq = q.shape[1]
    k = _gqa_expand(k, hq)
    v = _gqa_expand(v, hq)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(
            f"sequence {q.shape[2]} not divisible by {axis}={n}")
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ring_shard, axis_name=axis, causal=causal,
                          scale=q.shape[-1] ** -0.5, window=int(window)),
        # kv_lengths rides replicated (P()); a None is an empty pytree and
        # its spec is simply unused.
        mesh=mesh, in_specs=(spec, spec, spec, P()), out_specs=spec,
    )
    return fn(q, k, v, kv_lengths)


def make_ring_attention(mesh: Mesh, axis: str = "sp"):
    """Bind mesh/axis → a callable usable as ``attn_impl`` in the model
    forward passes (``models.decoder.forward(..., attn_impl=fn)``)."""
    return functools.partial(ring_attention, mesh=mesh, axis=axis)


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("ring-attention")
def _shardcheck_ring_attention():
    """Trace the shard_map'd ring under the real sp mesh: the psum /
    axis_index / ppermute collectives inside ``_ring_shard`` must bind
    the module's default axis on a mesh that actually has it, with the
    sequence divisible by the ring size. Uses the module defaults on
    purpose — an axis-name typo here IS the bug this catches."""
    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase,
        require_devices,
    )
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    mesh = build_mesh(MeshConfig(sp=4), devices=jax.devices()[:8])
    S = jax.ShapeDtypeStruct
    b, hq, hkv, s, d = 1, 8, 4, 256, 64
    q = S((b, hq, s, d), jnp.bfloat16)
    kv = S((b, hkv, s, d), jnp.bfloat16)
    return ContractCase(
        fn=functools.partial(ring_attention, mesh=mesh),
        args=(q, kv, kv),
        kwargs={"kv_lengths": S((b,), jnp.int32)},
        mesh=mesh,
    )
