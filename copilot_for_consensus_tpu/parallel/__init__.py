"""Device-mesh + sharding layer.

The reference scales horizontally with competing queue consumers and has no
model parallelism (SURVEY.md §2.3). The TPU-native equivalent is a
`jax.sharding.Mesh` over the slice with named axes:

* ``dp`` — data parallel (batch sharding for embed/prefill fan-out; the
  analogue of the reference's N competing consumers per queue),
* ``sp`` — sequence/context parallel (ring attention or Ulysses
  all-to-all for long contexts),
* ``ep`` — expert parallel (Mixtral MoE experts),
* ``tp`` — tensor parallel (weight sharding of the served LLM over ICI).

Collectives are emitted by XLA from shardings (pjit/GSPMD) — no NCCL/MPI;
that is the point of the TPU-first design (reference's inter-process comms
were RabbitMQ + HTTP, ``adapters/copilot_message_bus/``).
"""

from copilot_for_consensus_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    local_mesh,
)
from copilot_for_consensus_tpu.parallel.pipeline import (
    make_pipeline_train_step,
    pipeline_forward,
    shard_params_for_pipeline,
)
from copilot_for_consensus_tpu.parallel.ulysses import (
    make_ulysses_attention,
    ulysses_attention,
)
from copilot_for_consensus_tpu.parallel.sharding import (
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_to_spec,
    shard_pytree,
)

__all__ = [
    "MeshConfig",
    "build_mesh",
    "local_mesh",
    "LogicalAxisRules",
    "DEFAULT_RULES",
    "make_ulysses_attention",
    "ulysses_attention",
    "logical_to_spec",
    "shard_pytree",
    "pipeline_forward",
    "make_pipeline_train_step",
    "shard_params_for_pipeline",
]
