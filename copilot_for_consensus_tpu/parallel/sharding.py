"""Logical-axis → mesh-axis sharding rules (GSPMD style).

Model code annotates parameters with *logical* axis names
(``("vocab", "embed")``); the rules table maps those to mesh axes and
produces `PartitionSpec`s. Swapping a parallelism layout = swapping the
rules table, not the model code — the property that lets one model run
tp-only on 8 chips and dp×tp on a v5e-16 unchanged.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from copilot_for_consensus_tpu.analysis.contracts import (
    ContractCase,
    checkable,
    require_devices,
)

LogicalAxisRules = Mapping[str, str | tuple[str, ...] | None]

# Default serving layout: megatron-style TP over heads/ffn/vocab, batch on
# dp, sequence on sp (ring attention), experts on ep.
DEFAULT_RULES: LogicalAxisRules = {
    "batch": "dp",
    "seq": "sp",
    "embed": None,            # replicated: activations stay whole on-chip
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "ffn": "tp",
    "vocab": "tp",
    "experts": "ep",
    "expert_ffn": "tp",
    "norm": None,
}


def serving_param_rules(cfg, mesh: Mesh,
                        rules: LogicalAxisRules | None = None
                        ) -> LogicalAxisRules:
    """DEFAULT_RULES adjusted for a decoder config on a concrete mesh:
    replicate any HEAD-structured axis the tp degree does not divide.

    The fused projection leaves (``wk``/``wv``: ``[.., Hkv*Dh]``) are
    always divisible by tp in bytes, so a naive rules table shards them
    even when ``tp > n_kv_heads`` — which splits WITHIN ``head_dim``.
    That is semantically cursed (RoPE's rotate-half pairs columns
    ``i``/``i+Dh/2`` across the shard boundary) and, root-caused in
    PR 15, actually MISCOMPILES on the XLA CPU partitioner at some
    tile shapes (dp=2×tp=4 over Hkv=2 produced logits off by ~0.9 —
    the long-documented ``test_engine_on_mesh_matches_single_device``
    "environment failure"). Standard GQA serving replicates KV when tp
    exceeds the kv-head count; this helper applies exactly that rule,
    mirroring the cache-side fallback the engine has always had."""
    rules = dict(rules or DEFAULT_RULES)
    tp = mesh.shape.get("tp", 1)
    if tp > 1:
        if cfg.n_kv_heads % tp:
            rules["kv_heads"] = None
        if cfg.n_heads % tp:
            rules["heads"] = None
    return rules


def logical_to_spec(axes: Sequence[str | None],
                    rules: LogicalAxisRules | None = None) -> PartitionSpec:
    rules = rules or DEFAULT_RULES
    out: list[str | tuple[str, ...] | None] = []
    for ax in axes:
        if ax is None:
            out.append(None)
        elif ax in rules:
            out.append(rules[ax])
        else:
            # Fail loud: a typo'd axis name silently replicating a weight
            # is a memory blow-up, not a fallback.
            raise KeyError(f"unknown logical axis {ax!r}; rules know "
                           f"{sorted(rules)}")
    return PartitionSpec(*out)


def spec_tree(logical_tree: Any,
              rules: LogicalAxisRules | None = None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: logical_to_spec(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_pytree(tree: Any, logical_tree: Any, mesh: Mesh,
                 rules: LogicalAxisRules | None = None) -> Any:
    """Device-put a param pytree with shardings from its logical axes."""
    specs = spec_tree(logical_tree, rules)
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        tree,
        specs,
    )


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("serving-rules")
def _shardcheck_serving_rules():
    """DEFAULT_RULES must resolve to real axes of the serving meshes,
    and a Mistral-7B-class param tree (shapes via eval_shape — no
    memory) must divide evenly under them. A rule target the mesh
    lacks, or a dimension tp doesn't divide, silently replicates the
    weight instead of sharding it — the 2x-HBM bug class."""
    from copilot_for_consensus_tpu.models import decoder, decoder_config
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    cfg = decoder_config("mistral-7b")
    params = jax.eval_shape(
        lambda key: decoder.init_params(key, cfg), jax.random.PRNGKey(0))
    cache = jax.eval_shape(
        lambda: decoder.init_cache(cfg, 8, 256))
    devs = jax.devices()[:8]
    cases = []
    for label, mc in (("tp8", MeshConfig()),
                      ("dp2xtp4", MeshConfig(dp=2, tp=4))):
        mesh = build_mesh(mc, devices=devs)
        cases.append(ContractCase(
            label=label, mesh=mesh, rules=DEFAULT_RULES,
            logical=(
                ("params", params, decoder.logical_axes(cfg)),
                ("kv-cache", cache, decoder.cache_logical_axes()),
            )))
    return cases
