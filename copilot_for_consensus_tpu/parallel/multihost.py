"""Multi-host (multi-process) JAX runtime initialization.

The reference scales across machines with NCCL-less infrastructure —
RabbitMQ fan-out between service replicas (SURVEY §2.3). The TPU-native
equivalent is two-tier (SURVEY §5 "distributed communication backend"):
XLA collectives over ICI within a slice and DCN between hosts, which
requires every process in the job to join one JAX distributed runtime
before any device query. This module is that join, config-driven like
everything else (geometry comes from the config file, never from raw
environment reads — the repo's env-var policy test enforces this).

On Cloud TPU pods ``jax.distributed.initialize()`` auto-discovers the
coordinator and process ids from the TPU metadata; explicit settings
exist for CPU/GPU clusters, tests, and non-standard launchers. After
initialization, ``jax.devices()`` spans all hosts and
``parallel.mesh.build_mesh`` lays any dp/tp/pp/sp/ep mesh over the
global device set — collectives ride ICI within a host's chips and DCN
across hosts, inserted by XLA from the same shardings used everywhere
else (no separate code path).

Usage (engine-role process on each host of a slice):

    from copilot_for_consensus_tpu.parallel.multihost import (
        MultiHostConfig, initialize_multihost)
    initialize_multihost(MultiHostConfig(
        coordinator_address="host0:8476", num_processes=4, process_id=i))
    mesh = build_mesh(MeshConfig(dp=4, tp=4))   # global devices
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

_INITIALIZED = False


@dataclass(frozen=True)
class MultiHostConfig:
    """Explicit job geometry; every field None = TPU-pod auto-discovery.

    coordinator_address: "host:port" of process 0's coordinator service.
    num_processes: total processes in the job.
    process_id: this process's rank in [0, num_processes).
    local_device_ids: restrict this process to a subset of its local
        devices (rarely needed outside tests).
    """

    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None
    local_device_ids: tuple[int, ...] | None = None

    @classmethod
    def from_config(cls, cfg: Mapping[str, Any] | bool | None
                    ) -> "MultiHostConfig":
        # `multihost: true` in a config file means "auto-discover",
        # same as an empty section.
        c = dict(cfg) if isinstance(cfg, Mapping) else {}
        ids = c.get("local_device_ids")
        return cls(
            coordinator_address=c.get("coordinator_address"),
            num_processes=c.get("num_processes"),
            process_id=c.get("process_id"),
            local_device_ids=tuple(ids) if ids is not None else None,
        )

    @property
    def is_explicit(self) -> bool:
        return self.coordinator_address is not None

    def validate(self) -> None:
        if not self.is_explicit:
            if (self.num_processes is not None
                    or self.process_id is not None
                    or self.local_device_ids is not None):
                raise ValueError(
                    "num_processes/process_id/local_device_ids given "
                    "without coordinator_address — explicit geometry "
                    "needs the coordinator (or omit everything for "
                    "TPU-pod auto-discovery)")
            return
        if self.num_processes is None or self.process_id is None:
            raise ValueError(
                "explicit multihost config needs num_processes and "
                "process_id alongside coordinator_address")
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} out of range for "
                f"{self.num_processes} processes")


def initialize_multihost(cfg: MultiHostConfig | Mapping[str, Any] | None
                         = None) -> bool:
    """Join the JAX distributed runtime. Returns True if this call
    initialized it, False if it was a no-op (already initialized, or a
    single-process config). MUST run before the first device query."""
    global _INITIALIZED
    import jax

    if not isinstance(cfg, MultiHostConfig):
        cfg = MultiHostConfig.from_config(cfg)
    cfg.validate()
    if _INITIALIZED:
        return False
    if cfg.is_explicit and cfg.num_processes == 1:
        return False                       # nothing to coordinate
    kwargs: dict[str, Any] = {}
    if cfg.is_explicit:
        kwargs = {
            "coordinator_address": cfg.coordinator_address,
            "num_processes": cfg.num_processes,
            "process_id": cfg.process_id,
        }
        if cfg.local_device_ids is not None:
            kwargs["local_device_ids"] = list(cfg.local_device_ids)
        jax.distributed.initialize(**kwargs)
    else:
        # TPU-pod auto-discovery; harmless single-process no-op is NOT
        # guaranteed here, so only auto-init when a pod environment is
        # plausible — callers on one host simply skip the call.
        jax.distributed.initialize()
    _INITIALIZED = True
    return True


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def is_multihost() -> bool:
    return process_count() > 1
