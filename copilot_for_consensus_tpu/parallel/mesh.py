"""Mesh construction for single-host, multi-chip, and multi-slice runs."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

from copilot_for_consensus_tpu.analysis.contracts import checkable

MESH_AXES = ("dp", "pp", "sp", "ep", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. Product must equal the device count.

    Leave ``tp`` at 0 to auto-fill it with the remaining devices (serving
    default: shard the model), or set ``tp`` and leave ``dp`` at 0 to
    auto-fill the data axis instead.
    """

    dp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 0

    def resolve(self, n_devices: int) -> "MeshConfig":
        dp, pp, sp, ep, tp = self.dp, self.pp, self.sp, self.ep, self.tp
        if tp == 0:
            fixed = max(1, dp) * max(1, pp) * max(1, sp) * max(1, ep)
            if n_devices % fixed:
                raise ValueError(
                    f"mesh axes dp={dp} pp={pp} sp={sp} ep={ep} do not "
                    f"divide {n_devices} devices"
                )
            tp = n_devices // fixed
        elif dp == 0:
            fixed = max(1, pp) * max(1, sp) * max(1, ep) * tp
            if n_devices % fixed:
                raise ValueError(
                    f"mesh axes pp={pp} sp={sp} ep={ep} tp={tp} do not "
                    f"divide {n_devices} devices"
                )
            dp = n_devices // fixed
        total = max(1, dp) * max(1, pp) * max(1, sp) * max(1, ep) * tp
        if total != n_devices:
            raise ValueError(
                f"mesh {dp}x{pp}x{sp}x{ep}x{tp}={total} != "
                f"{n_devices} devices"
            )
        return MeshConfig(max(1, dp), max(1, pp), max(1, sp), max(1, ep),
                          tp)

    @property
    def shape(self) -> tuple[int, int, int, int, int]:
        return (self.dp, self.pp, self.sp, self.ep, self.tp)


def build_mesh(config: MeshConfig | None = None,
               devices: list | None = None) -> Mesh:
    """Build the 4-axis mesh over all (or the given) devices.

    Axis order is (dp, sp, ep, tp) with tp innermost so tensor-parallel
    collectives ride the fastest ICI links, the standard TPU layout.
    """
    devs = devices if devices is not None else jax.devices()
    cfg = (config or MeshConfig()).resolve(len(devs))
    arr = np.array(devs).reshape(cfg.shape)
    return Mesh(arr, MESH_AXES)


def local_mesh(tp: int | None = None) -> Mesh:
    """Convenience single-axis-of-interest mesh on local devices: all tp."""
    n = len(jax.devices())
    t = tp or n
    if n % t:
        raise ValueError(f"tp={t} does not divide {n} devices")
    return build_mesh(MeshConfig(dp=n // t, tp=t))


def retrieval_mesh(n_devices: int | None = None) -> Mesh:
    """ANN retrieval plane (vectorstore/ivf.py): dp-only mesh — every
    device owns one posting-list shard, no tp axis because the
    candidate rescore is a shard-local matvec with a host top-k merge
    (no collectives in the search dispatch, by contract)."""
    n = n_devices if n_devices is not None else len(jax.devices())
    return build_mesh(MeshConfig(dp=n, tp=1), devices=jax.devices()[:n])


def largest_pow2_leq(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n else 1


def auto_mesh_for_serving(n_devices: int | None = None) -> Mesh:
    """Serving default: tp = largest power of two ≤ device count, dp rest."""
    n = n_devices if n_devices is not None else len(jax.devices())
    tp = largest_pow2_leq(n)
    while n % tp:
        tp //= 2
    return build_mesh(MeshConfig(dp=n // tp, tp=tp),
                      devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("serving-meshes")
def _shardcheck_serving_meshes():
    """Every mesh this module can build for serving must carry every
    axis the sharding rules target — MESH_AXES and
    ``sharding.DEFAULT_RULES`` are maintained in different files, and a
    rename on either side must fail CI, not replicate weights."""
    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase,
        require_devices,
    )
    from copilot_for_consensus_tpu.parallel.sharding import DEFAULT_RULES

    require_devices(8)
    devs = jax.devices()[:8]
    return [
        ContractCase(label="serving-default",
                     mesh=build_mesh(MeshConfig(), devices=devs),
                     rules=DEFAULT_RULES),
        ContractCase(label="auto-serving",
                     mesh=auto_mesh_for_serving(8),
                     rules=DEFAULT_RULES),
        ContractCase(label="sp4",
                     mesh=build_mesh(MeshConfig(sp=4), devices=devs),
                     rules=DEFAULT_RULES),
    ]
