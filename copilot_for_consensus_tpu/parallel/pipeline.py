"""Pipeline parallelism over the ``pp`` mesh axis (SPMD GPipe).

SURVEY.md §2.3 lists layer-pipeline parallelism as the TPU-native
equivalent of multi-slice scaling: when a model's layer stack exceeds one
slice's HBM, stages hold contiguous layer spans and microbatches stream
through. Built the SPMD way — NOT a per-stage program: every device runs
the SAME jitted program under ``shard_map``; ``lax.axis_index('pp')``
selects the stage's behavior, activations hop stage→stage over ICI via
``ppermute``, and bubble steps compute-and-discard (masking is cheaper
than idling inside one traced program). This is the schedule jax/praxis
use for TPU pipelining, and gradients flow through ``ppermute``
automatically, so the same function trains.

Schedule: M microbatches over P stages take M + P - 1 steps; each step
every stage runs its local L/P layers once. The last stage's outputs are
masked-psum'd back to all devices (cheap at [B, S, D] test scale; a
multi-slice deployment would leave them stage-local for the loss).

Layer weights shard their leading (layer-stack) axis over ``pp`` — the
``layers`` logical axis below. With ``tp_axis`` set, each stage ALSO
tensor-parallelizes its layers Megatron-style inside the shard_map
body: qkv and gate/up are column-parallel (no communication), wo and
w_down are row-parallel, and the two partial products psum over ``tp``
per layer — heads and ffn width divide across the tp ranks, so a
pp×tp mesh holds 1/(pp·tp) of the stack per device.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:   # jax < 0.5 exports it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from copilot_for_consensus_tpu.analysis.contracts import checkable
from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.parallel.sharding import (
    DEFAULT_RULES,
    shard_pytree,
)

PIPELINE_RULES = dict(DEFAULT_RULES, layers="pp")


def pipeline_logical_axes(cfg: DecoderConfig) -> Any:
    """decoder.logical_axes with the layer-stack axis named ``layers`` so
    it shards over pp (the serving tables leave it None = replicated)."""
    axes = decoder.logical_axes(cfg)
    axes["layers"] = {
        k: ("layers",) + tuple(v[1:]) for k, v in axes["layers"].items()
    }
    return axes


def shard_params_for_pipeline(params: Any, cfg: DecoderConfig,
                              mesh: Mesh) -> Any:
    return shard_pytree(params, pipeline_logical_axes(cfg), mesh,
                        PIPELINE_RULES)


def _block_tp(x, layer, cfg_local, lengths, impl, tp_axis):
    """One transformer block with its heads/ffn width SPLIT over
    ``tp_axis`` (the leaves in ``layer`` are already the local shards).
    The wo and w_down products are partial sums — ``decoder.block``'s
    ``reduce`` hook psums each (the standard column→row Megatron
    schedule: two collectives per layer), so the block body itself
    stays single-sourced in decoder.py."""
    return decoder.block(x, layer, cfg_local, lengths, impl,
                         reduce=lambda t: jax.lax.psum(t, tp_axis))


def _pp_shard(layers_local, x_mb, lengths, *, axis, cfg, impl,
              tp_axis=None):
    """Per-device body. layers_local: this stage's layer span (leading dim
    L/P; head/ffn axes further split over ``tp_axis`` when set);
    x_mb: [M, mb, S, D] microbatched embeddings (replicated);
    lengths: [M, mb] (replicated)."""
    pp = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    m = x_mb.shape[0]
    steps = m + pp - 1
    perm = [(i, i + 1) for i in range(pp - 1)]       # no wraparound

    if hasattr(jax.lax, "pcast"):
        vary = lambda t: jax.lax.pcast(
            t, (axis,), to="varying")  # noqa: E731
    else:   # jax < 0.7: no varying/unvarying type system
        vary = lambda t: t  # noqa: E731

    if tp_axis is not None:
        import dataclasses

        tp = jax.lax.psum(1, tp_axis)
        cfg_local = dataclasses.replace(
            cfg, n_heads=cfg.n_heads // tp,
            n_kv_heads=cfg.n_kv_heads // tp, d_ff=cfg.d_ff // tp,
            head_dim_override=cfg.head_dim)

        def run_stage(x, mb_lengths):
            def body(x, layer):
                return _block_tp(x, layer, cfg_local, mb_lengths, impl,
                                 tp_axis), None
            x, _ = jax.lax.scan(body, x, layers_local)
            return x
    else:
        def run_stage(x, mb_lengths):
            def body(x, layer):
                return decoder.block(x, layer, cfg, mb_lengths, impl), None
            x, _ = jax.lax.scan(body, x, layers_local)
            return x

    def body(t, carry):
        recv, out_buf = carry
        # Stage 0 pulls the next microbatch from the queue; later stages
        # consume what the previous stage sent last step.
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, vary(x_mb)[mb_idx], recv)
        mb_lengths = vary(lengths)[jnp.clip(t - stage, 0, m - 1)]
        y = run_stage(inp, mb_lengths)
        # The last stage finished microbatch t-(pp-1) this step.
        w = t - (pp - 1)
        valid = (stage == pp - 1) & (w >= 0)
        upd = jax.lax.dynamic_update_slice_in_dim(
            out_buf, y[None], jnp.clip(w, 0, m - 1), axis=0)
        out_buf = jnp.where(valid, upd, out_buf)
        recv = jax.lax.ppermute(y, axis, perm)
        return recv, out_buf

    recv0 = vary(jnp.zeros(x_mb.shape[1:], x_mb.dtype))
    out0 = vary(jnp.zeros_like(x_mb))
    _, out_buf = jax.lax.fori_loop(0, steps, body, (recv0, out0))
    # Only the last stage's buffer is real; psum broadcasts it.
    return jax.lax.psum(
        jnp.where(stage == pp - 1, out_buf, jnp.zeros_like(out_buf)),
        axis)


#: which axis of each layer leaf splits over tp (column-parallel out
#: axes for qkv/gate/up, row-parallel in axes for wo/down); norms stay
#: replicated.
_TP_LEAF_AXIS = {"wq": 2, "wk": 2, "wv": 2, "w_gate": 2, "w_up": 2,
                 "wo": 1, "w_down": 1}


def pipeline_forward(params: Any, tokens: jax.Array, cfg: DecoderConfig,
                     mesh: Mesh, *, n_microbatches: int,
                     lengths: jax.Array | None = None,
                     axis: str = "pp", tp_axis: str | None = None,
                     attn_impl: str = "auto") -> jax.Array:
    """[B, S] tokens → [B, S, V] fp32 logits with the layer stack
    pipelined over ``axis`` and (optionally) each stage's heads/ffn
    width tensor-parallel over ``tp_axis``. Embed/unembed run
    replicated outside the pipeline (they are one matmul each; the
    stack dominates)."""
    b, s = tokens.shape
    m = n_microbatches
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    if cfg.n_layers % mesh.shape[axis]:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {axis}="
            f"{mesh.shape[axis]} stages")
    if tp_axis is not None:
        tp = mesh.shape[tp_axis]
        if cfg.is_moe:
            raise ValueError("intra-stage tp does not cover MoE layers")
        for dim, nm in ((cfg.n_heads, "n_heads"),
                        (cfg.n_kv_heads, "n_kv_heads"),
                        (cfg.d_ff, "d_ff")):
            if dim % tp:
                raise ValueError(f"{nm}={dim} not divisible by "
                                 f"{tp_axis}={tp}")
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)

    x = params["tok_emb"][tokens]                     # [B, S, D]
    x_mb = x.reshape(m, b // m, s, x.shape[-1])
    len_mb = lengths.reshape(m, b // m)

    def leaf_spec(path, leaf):
        name = path[-1].key
        dims = [axis] + [None] * (leaf.ndim - 1)
        if tp_axis is not None and name in _TP_LEAF_AXIS:
            dims[_TP_LEAF_AXIS[name]] = tp_axis
        return P(*dims)

    layer_specs = jax.tree_util.tree_map_with_path(
        leaf_spec, params["layers"])
    fn = shard_map(
        functools.partial(_pp_shard, axis=axis, cfg=cfg, impl=attn_impl,
                          tp_axis=tp_axis),
        mesh=mesh,
        in_specs=(layer_specs, P(), P()),
        out_specs=P(),
    )
    y = fn(params["layers"], x_mb, len_mb)
    y = y.reshape(b, s, -1)
    return decoder._unembed(y, params, cfg)


def pipeline_greedy_decode(params: Any, prompt: jax.Array,
                           cfg: DecoderConfig, mesh: Mesh, *,
                           n_new_tokens: int, n_microbatches: int = 1,
                           axis: str = "pp", tp_axis: str | None = None,
                           attn_impl: str = "auto") -> jax.Array:
    """Greedy decode THROUGH the pp(×tp) pipeline: each step re-runs the
    pipelined forward over the grown sequence and appends the argmax
    token. prompt: [B, S] → returns [B, n_new_tokens].

    This is the prefill-style serving path for the pipelined stack
    (batch scoring / short generations where the layer stack doesn't
    fit one slice); a KV-cached windowed pp decode is the long-form
    follow-up. The sequence buffer is padded once so every step runs
    the SAME program shape (one compile), with ``lengths`` masking the
    not-yet-generated tail."""
    b, s0 = prompt.shape
    buf = jnp.concatenate(
        [prompt, jnp.zeros((b, n_new_tokens), prompt.dtype)], axis=1)

    def step(carry, _):
        buf, n = carry
        lengths = jnp.full((b,), n, jnp.int32)
        logits = pipeline_forward(
            params, buf, cfg, mesh, n_microbatches=n_microbatches,
            lengths=lengths, axis=axis, tp_axis=tp_axis,
            attn_impl=attn_impl)
        # argmax at each row's last valid position
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        nxt = jnp.argmax(last, axis=-1).astype(buf.dtype)
        buf = jax.vmap(
            lambda row, pos, tok: jax.lax.dynamic_update_slice(
                row, tok[None], (pos,)))(buf, lengths, nxt)
        return (buf, n + 1), nxt

    (_, _), toks = jax.lax.scan(step, (buf, jnp.int32(s0)),
                                None, length=n_new_tokens)
    return toks.T                                     # [B, n_new]


def make_pipeline_train_step(cfg: DecoderConfig, optimizer, mesh: Mesh,
                             *, n_microbatches: int,
                             attn_impl: str = "xla"):
    """Training step with the layer stack pipelined — the pp counterpart
    of ``train.make_train_step`` (which supplies the loss and optimizer
    wiring; only the forward pass is swapped). Gradients flow through
    ppermute; jit it with params sharded by
    ``shard_params_for_pipeline``. Defaults to XLA attention: the Pallas
    flash kernel is forward-only (no JVP), see train.py."""
    from copilot_for_consensus_tpu import train

    def fwd(params, tokens, cfg, lengths=None, attn_impl=attn_impl):
        return pipeline_forward(params, tokens, cfg, mesh,
                                n_microbatches=n_microbatches,
                                lengths=lengths, attn_impl=attn_impl)

    return train.make_train_step(cfg, optimizer, attn_impl=attn_impl,
                                 forward_fn=fwd)


# ---------------------------------------------------------------------------
# shardcheck contracts (analysis/shardcheck.py)
# ---------------------------------------------------------------------------


@checkable("pipeline-forward")
def _shardcheck_pipeline_forward():
    """Trace the SPMD pipeline under a real pp(×tp) mesh: the
    axis_index / ppermute / psum collectives in ``_pp_shard`` (and the
    per-layer tp psums of ``_block_tp``) must bind axes the mesh has,
    and the PIPELINE_RULES layer-stack sharding must divide the layer
    leaves evenly. Param shapes come from eval_shape — nothing is
    allocated."""
    from copilot_for_consensus_tpu.analysis.contracts import (
        ContractCase,
        require_devices,
    )
    from copilot_for_consensus_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    require_devices(8)
    cfg = DecoderConfig(name="shardcheck-tiny", vocab_size=64,
                        d_model=32, n_layers=4, n_heads=4, n_kv_heads=2,
                        d_ff=64, max_seq_len=64)
    params = jax.eval_shape(
        lambda key: decoder.init_params(key, cfg), jax.random.PRNGKey(0))
    # pp2×tp2 (dp auto-fills to 2): layers 4 / pp 2, heads 4 & kv 2 &
    # ffn 64 / tp 2 — the divisibilities pipeline_forward relies on.
    mesh = build_mesh(MeshConfig(dp=0, pp=2, tp=2),
                      devices=jax.devices()[:8])
    tokens = jax.ShapeDtypeStruct((4, 32), jnp.int32)
    return [
        ContractCase(
            label="pp-only", mesh=mesh, rules=PIPELINE_RULES,
            logical=(("pipeline-params", params,
                      pipeline_logical_axes(cfg)),),
            fn=lambda p, t: pipeline_forward(
                p, t, cfg, mesh, n_microbatches=2, attn_impl="xla"),
            args=(params, tokens),
        ),
        ContractCase(
            label="pp-x-tp", mesh=mesh,
            fn=lambda p, t: pipeline_forward(
                p, t, cfg, mesh, n_microbatches=2, tp_axis="tp",
                attn_impl="xla"),
            args=(params, tokens),
        ),
    ]
