"""Pipeline parallelism over the ``pp`` mesh axis (SPMD GPipe).

SURVEY.md §2.3 lists layer-pipeline parallelism as the TPU-native
equivalent of multi-slice scaling: when a model's layer stack exceeds one
slice's HBM, stages hold contiguous layer spans and microbatches stream
through. Built the SPMD way — NOT a per-stage program: every device runs
the SAME jitted program under ``shard_map``; ``lax.axis_index('pp')``
selects the stage's behavior, activations hop stage→stage over ICI via
``ppermute``, and bubble steps compute-and-discard (masking is cheaper
than idling inside one traced program). This is the schedule jax/praxis
use for TPU pipelining, and gradients flow through ``ppermute``
automatically, so the same function trains.

Schedule: M microbatches over P stages take M + P - 1 steps; each step
every stage runs its local L/P layers once. The last stage's outputs are
masked-psum'd back to all devices (cheap at [B, S, D] test scale; a
multi-slice deployment would leave them stage-local for the loss).

Layer weights shard their leading (layer-stack) axis over ``pp`` — the
``layers`` logical axis below. Parallelism here is pp-only: the explicit
shard_map specs replicate weights/activations over every other mesh axis,
so meshes with tp/dp > 1 are correct but redundant inside the pipeline
(intra-stage tp would need manual collectives in the stage body — a
follow-up, not a property of this module yet).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from copilot_for_consensus_tpu.models import decoder
from copilot_for_consensus_tpu.models.configs import DecoderConfig
from copilot_for_consensus_tpu.parallel.sharding import (
    DEFAULT_RULES,
    shard_pytree,
)

PIPELINE_RULES = dict(DEFAULT_RULES, layers="pp")


def pipeline_logical_axes(cfg: DecoderConfig) -> Any:
    """decoder.logical_axes with the layer-stack axis named ``layers`` so
    it shards over pp (the serving tables leave it None = replicated)."""
    axes = decoder.logical_axes(cfg)
    axes["layers"] = {
        k: ("layers",) + tuple(v[1:]) for k, v in axes["layers"].items()
    }
    return axes


def shard_params_for_pipeline(params: Any, cfg: DecoderConfig,
                              mesh: Mesh) -> Any:
    return shard_pytree(params, pipeline_logical_axes(cfg), mesh,
                        PIPELINE_RULES)


def _pp_shard(layers_local, x_mb, lengths, *, axis, cfg, impl):
    """Per-device body. layers_local: this stage's layer span (leading dim
    L/P); x_mb: [M, mb, S, D] microbatched embeddings (replicated);
    lengths: [M, mb] (replicated)."""
    pp = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    m = x_mb.shape[0]
    steps = m + pp - 1
    perm = [(i, i + 1) for i in range(pp - 1)]       # no wraparound

    vary = lambda t: jax.lax.pcast(t, (axis,), to="varying")  # noqa: E731

    def run_stage(x, mb_lengths):
        def body(x, layer):
            return decoder.block(x, layer, cfg, mb_lengths, impl), None
        x, _ = jax.lax.scan(body, x, layers_local)
        return x

    def body(t, carry):
        recv, out_buf = carry
        # Stage 0 pulls the next microbatch from the queue; later stages
        # consume what the previous stage sent last step.
        mb_idx = jnp.clip(t, 0, m - 1)
        inp = jnp.where(stage == 0, vary(x_mb)[mb_idx], recv)
        mb_lengths = vary(lengths)[jnp.clip(t - stage, 0, m - 1)]
        y = run_stage(inp, mb_lengths)
        # The last stage finished microbatch t-(pp-1) this step.
        w = t - (pp - 1)
        valid = (stage == pp - 1) & (w >= 0)
        upd = jax.lax.dynamic_update_slice_in_dim(
            out_buf, y[None], jnp.clip(w, 0, m - 1), axis=0)
        out_buf = jnp.where(valid, upd, out_buf)
        recv = jax.lax.ppermute(y, axis, perm)
        return recv, out_buf

    recv0 = vary(jnp.zeros(x_mb.shape[1:], x_mb.dtype))
    out0 = vary(jnp.zeros_like(x_mb))
    _, out_buf = jax.lax.fori_loop(0, steps, body, (recv0, out0))
    # Only the last stage's buffer is real; psum broadcasts it.
    return jax.lax.psum(
        jnp.where(stage == pp - 1, out_buf, jnp.zeros_like(out_buf)),
        axis)


def pipeline_forward(params: Any, tokens: jax.Array, cfg: DecoderConfig,
                     mesh: Mesh, *, n_microbatches: int,
                     lengths: jax.Array | None = None,
                     axis: str = "pp", attn_impl: str = "auto"
                     ) -> jax.Array:
    """[B, S] tokens → [B, S, V] fp32 logits with the layer stack
    pipelined over ``axis``. Embed/unembed run replicated outside the
    pipeline (they are one matmul each; the stack dominates)."""
    b, s = tokens.shape
    m = n_microbatches
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    if cfg.n_layers % mesh.shape[axis]:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {axis}="
            f"{mesh.shape[axis]} stages")
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)

    x = params["tok_emb"][tokens]                     # [B, S, D]
    x_mb = x.reshape(m, b // m, s, x.shape[-1])
    len_mb = lengths.reshape(m, b // m)

    layer_specs = jax.tree.map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))),
        params["layers"])
    fn = shard_map(
        functools.partial(_pp_shard, axis=axis, cfg=cfg, impl=attn_impl),
        mesh=mesh,
        in_specs=(layer_specs, P(), P()),
        out_specs=P(),
    )
    y = fn(params["layers"], x_mb, len_mb)
    y = y.reshape(b, s, -1)
    return decoder._unembed(y, params, cfg)


def make_pipeline_train_step(cfg: DecoderConfig, optimizer, mesh: Mesh,
                             *, n_microbatches: int,
                             attn_impl: str = "xla"):
    """Training step with the layer stack pipelined — the pp counterpart
    of ``train.make_train_step`` (which supplies the loss and optimizer
    wiring; only the forward pass is swapped). Gradients flow through
    ppermute; jit it with params sharded by
    ``shard_params_for_pipeline``. Defaults to XLA attention: the Pallas
    flash kernel is forward-only (no JVP), see train.py."""
    from copilot_for_consensus_tpu import train

    def fwd(params, tokens, cfg, lengths=None, attn_impl=attn_impl):
        return pipeline_forward(params, tokens, cfg, mesh,
                                n_microbatches=n_microbatches,
                                lengths=lengths, attn_impl=attn_impl)

    return train.make_train_step(cfg, optimizer, attn_impl=attn_impl,
                                 forward_fn=fwd)
