"""DocumentStore ABC + the Mongo-style filter subset shared by drivers.

Filter language (enough for every query the pipeline makes):
equality, ``$ne``, ``$in``, ``$nin``, ``$exists``, ``$lt/$lte/$gt/$gte``,
``$regex``, ``$or`` (list of sub-filters), and dotted paths for nested
fields.
"""

from __future__ import annotations

import abc
import re
from typing import Any, Iterable, Mapping, Sequence

from copilot_for_consensus_tpu.core.retry import RetryableError


class StorageError(Exception):
    pass


class DuplicateKeyError(StorageError):
    """Insert with an already-present primary key (idempotent stages catch
    this and treat it as success — reference behavior at
    ``chunking/app/service.py:343``)."""


class StorageContentionError(StorageError, RetryableError):
    """Transient lock/contention inside the store (sqlite ``database is
    locked`` under concurrent writers, Cosmos 429s, ...). Being a
    :class:`RetryableError` it rides the in-process retry + backoff and
    then the bus lease/redelivery path — infrastructure contention must
    never be classified as poison and quarantined (diagnosed from a
    ``pipeline_chaos`` storm where 35 locked writes dead-lettered good
    work; ``docs/RESILIENCE.md`` poison-vs-transient table)."""


def _resolve_path(doc: Mapping[str, Any], path: str):
    node: Any = doc
    for part in path.split("."):
        if not isinstance(node, Mapping) or part not in node:
            return None, False
        node = node[part]
    return node, True


_OPS = {
    "$ne": lambda value, arg: value != arg,
    "$in": lambda value, arg: value in arg,
    "$nin": lambda value, arg: value not in arg,
    "$lt": lambda value, arg: value is not None and value < arg,
    "$lte": lambda value, arg: value is not None and value <= arg,
    "$gt": lambda value, arg: value is not None and value > arg,
    "$gte": lambda value, arg: value is not None and value >= arg,
    "$regex": lambda value, arg: isinstance(value, str) and re.search(arg, value) is not None,
}


def _match_condition(doc: Mapping[str, Any], path: str, cond: Any) -> bool:
    value, exists = _resolve_path(doc, path)
    if isinstance(cond, Mapping) and any(k.startswith("$") for k in cond):
        for op, arg in cond.items():
            if op == "$exists":
                if bool(arg) != exists:
                    return False
            elif op in _OPS:
                if not exists and op != "$ne":
                    return False
                if not _OPS[op](value, arg):
                    return False
            else:
                raise StorageError(f"unsupported filter operator {op!r}")
        return True
    return exists and value == cond


def matches_filter(doc: Mapping[str, Any], flt: Mapping[str, Any] | None) -> bool:
    if not flt:
        return True
    for key, cond in flt.items():
        if key == "$or":
            if not any(matches_filter(doc, sub) for sub in cond):
                return False
        elif key == "$and":
            if not all(matches_filter(doc, sub) for sub in cond):
                return False
        elif not _match_condition(doc, key, cond):
            return False
    return True


def sort_documents(docs: list[dict], sort: Sequence[tuple[str, int]] | None) -> list[dict]:
    if not sort:
        return docs
    for field_name, direction in reversed(list(sort)):
        docs.sort(
            key=lambda d: ((v := _resolve_path(d, field_name)[0]) is None, v),
            reverse=direction < 0,
        )
    return docs


class DocumentStore(abc.ABC):
    """CRUD + query over named collections of JSON documents."""

    def connect(self) -> None:
        pass

    def close(self) -> None:
        pass

    @abc.abstractmethod
    def insert_document(self, collection: str, doc: Mapping[str, Any]) -> str:
        """Insert; raises DuplicateKeyError if the primary key exists."""

    @abc.abstractmethod
    def upsert_document(self, collection: str, doc: Mapping[str, Any]) -> str: ...

    @abc.abstractmethod
    def get_document(self, collection: str, doc_id: str) -> dict[str, Any] | None: ...

    @abc.abstractmethod
    def query_documents(self, collection: str,
                        flt: Mapping[str, Any] | None = None, *,
                        limit: int | None = None, skip: int = 0,
                        sort: Sequence[tuple[str, int]] | None = None
                        ) -> list[dict[str, Any]]: ...

    @abc.abstractmethod
    def update_document(self, collection: str, doc_id: str,
                        updates: Mapping[str, Any]) -> bool:
        """Shallow-merge updates into the doc; False if absent."""

    @abc.abstractmethod
    def delete_document(self, collection: str, doc_id: str) -> bool: ...

    @abc.abstractmethod
    def delete_documents(self, collection: str,
                         flt: Mapping[str, Any] | None = None) -> int: ...

    @abc.abstractmethod
    def count_documents(self, collection: str,
                        flt: Mapping[str, Any] | None = None) -> int: ...

    def insert_or_ignore(self, collection: str, doc: Mapping[str, Any]) -> bool:
        """Idempotent insert: True if inserted, False if already present."""
        try:
            self.insert_document(collection, doc)
            return True
        except DuplicateKeyError:
            return False

    def insert_many(self, collection: str, docs: Iterable[Mapping[str, Any]],
                    ignore_duplicates: bool = True) -> int:
        n = 0
        for doc in docs:
            if ignore_duplicates:
                n += int(self.insert_or_ignore(collection, doc))
            else:
                self.insert_document(collection, doc)
                n += 1
        return n

    def get_documents(self, collection: str,
                      doc_ids: Sequence[str]) -> dict[str, dict[str, Any]]:
        """Multi-get: ``{doc_id: doc}`` for the ids that exist (missing
        ids are simply absent — callers decide whether absence is an
        error). Drivers override with one round-trip; this default
        loops :meth:`get_document` so every backend keeps exact
        semantics."""
        out: dict[str, dict[str, Any]] = {}
        for doc_id in doc_ids:
            key = str(doc_id)
            if key in out:
                continue
            doc = self.get_document(collection, key)
            if doc is not None:
                out[key] = doc
        return out

    def update_documents(self, collection: str, doc_ids: Sequence[str],
                         updates: Mapping[str, Any]) -> int:
        """Bulk shallow-merge of the SAME updates into many docs;
        returns how many existed. Drivers override with one
        transaction; the default loops :meth:`update_document`."""
        n = 0
        seen: set[str] = set()
        for doc_id in doc_ids:
            key = str(doc_id)
            if key in seen:
                continue
            seen.add(key)
            n += int(self.update_document(collection, key, updates))
        return n

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.close()
