"""Azure Cosmos DB (SQL API) document store — raw REST, no SDK.

The reference's ``AzureCosmosDocumentStore``
(``copilot_storage/azure_cosmos_document_store.py``, 1,077 LoC on the
Azure SDK) fills the cloud-production role next to Mongo; here the
driver speaks the Cosmos REST API directly with stdlib HTTP:

* **Auth**: master-key HMAC-SHA256 over the documented canonical string
  (verb, resource type, resource link, x-ms-date) — same zero-SDK
  approach as ``archive/azure_blob.py``.
* **Filters**: the store contract's Mongo-subset filters translate to
  parameterized Cosmos SQL (``translate_filter`` — equality, $ne, $in,
  $nin, $lt/$lte/$gt/$gte, $exists, $regex → RegexMatch, $or/$and,
  dotted paths). The translator is pure and unit-tested; the
  wire-contract mock in ``tests/test_azure_drivers.py`` evaluates the
  emitted SQL grammar, so filter → SQL → result round-trips are tested
  end-to-end without Cosmos.
* **Layout**: one container per collection (created lazily, 409
  tolerated), partition key ``/id``, the registry primary key mapped to
  Cosmos ``id``.

Usable against real Cosmos or its emulator wherever egress exists; this
image has neither, hence the wire-contract tests.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.error
import urllib.parse
import urllib.request
from email.utils import formatdate
from typing import Any, Mapping

from copilot_for_consensus_tpu.storage import registry
from copilot_for_consensus_tpu.storage.base import (
    DocumentStore,
    DuplicateKeyError,
    StorageError,
)

_SQL_OPS = {"$lt": "<", "$lte": "<=", "$gt": ">", "$gte": ">="}


def sql_field(path: str) -> str:
    """Dotted path → ``c.a.b`` with the charset validated — shared by
    the filter translator and ORDER BY so neither can interpolate
    hostile text."""
    parts = str(path).split(".")
    if not all(p and all(c.isascii() and (c.isalnum() or c == "_")
                         for c in p) for p in parts):
        raise StorageError(f"unsupported field path {path!r}")
    return "c." + ".".join(parts)


def translate_filter(flt: Mapping[str, Any] | None
                     ) -> tuple[str, list[dict[str, Any]]]:
    """Mongo-subset filter → (WHERE clause, Cosmos parameters).

    Returns ``("", [])`` for an empty filter. Dotted paths become
    ``c.a.b``; every literal becomes an ``@pN`` parameter (never
    inlined — injection-safe by construction)."""
    params: list[dict[str, Any]] = []

    def bind(value: Any) -> str:
        name = f"@p{len(params)}"
        params.append({"name": name, "value": value})
        return name

    field = sql_field

    def condition(path: str, cond: Any) -> str:
        f = field(path)
        if isinstance(cond, Mapping) and any(
                str(k).startswith("$") for k in cond):
            terms = []
            for op, arg in cond.items():
                if op == "$exists":
                    terms.append(f"IS_DEFINED({f})" if arg
                                 else f"NOT IS_DEFINED({f})")
                elif op == "$in":
                    terms.append(
                        f"ARRAY_CONTAINS({bind(list(arg))}, {f})")
                elif op == "$nin":
                    terms.append(
                        f"NOT ARRAY_CONTAINS({bind(list(arg))}, {f})")
                elif op == "$regex":
                    terms.append(f"RegexMatch({f}, {bind(arg)})")
                elif op == "$ne":
                    # base-contract semantics: $ne MATCHES docs missing
                    # the field; bare != is undefined for them in Cosmos
                    terms.append(f"(NOT IS_DEFINED({f}) OR "
                                 f"{f} != {bind(arg)})")
                elif op in _SQL_OPS:
                    terms.append(f"{f} {_SQL_OPS[op]} {bind(arg)}")
                else:
                    raise StorageError(
                        f"unsupported filter operator {op!r}")
            return " AND ".join(terms)
        return f"{f} = {bind(cond)}"

    def clause(sub: Mapping[str, Any]) -> str:
        terms = []
        for key, cond in sub.items():
            if key == "$or":
                # empty $or matches nothing (any([]) in the base
                # contract); '()' would be an opaque Cosmos 400
                terms.append("(" + " OR ".join(
                    f"({clause(s)})" for s in cond) + ")"
                    if cond else "false")
            elif key == "$and":
                # empty $and is vacuously true (all([]))
                terms.append("(" + " AND ".join(
                    f"({clause(s)})" for s in cond) + ")"
                    if cond else "true")
            else:
                terms.append(condition(key, cond))
        return " AND ".join(terms) if terms else "true"

    if not flt:
        return "", params
    return clause(flt), params


class AzureCosmosDocumentStore(DocumentStore):
    API_VERSION = "2018-12-31"

    def __init__(self, account: str, master_key: str,
                 database: str = "copilot", *, endpoint: str = "",
                 timeout_s: float = 30.0):
        if not account or not master_key:
            raise ValueError("azure_cosmos needs account and master_key")
        self.account = account
        self.master_key = master_key
        self.database = database
        self.endpoint = (endpoint.rstrip("/")
                         or f"https://{account}.documents.azure.com")
        self.timeout_s = timeout_s
        self._known_colls: set[str] = set()
        self._connected = False

    # -- wire plumbing --------------------------------------------------

    def _auth(self, verb: str, resource_type: str, resource_link: str,
              date: str) -> str:
        payload = (f"{verb.lower()}\n{resource_type.lower()}\n"
                   f"{resource_link}\n{date.lower()}\n\n")
        sig = base64.b64encode(
            hmac.new(base64.b64decode(self.master_key),
                     payload.encode(), hashlib.sha256).digest()).decode()
        return urllib.parse.quote(
            f"type=master&ver=1.0&sig={sig}", safe="")

    def _request(self, verb: str, resource_type: str,
                 resource_link: str, path: str,
                 body: dict | None = None,
                 headers: dict[str, str] | None = None,
                 ok: tuple[int, ...] = (200, 201),
                 content_type: str = "application/json",
                 notfound_ok: bool = False
                 ) -> tuple[int, dict | None]:
        date = formatdate(time.time(), usegmt=True)
        hdrs = {
            "x-ms-date": date,
            "x-ms-version": self.API_VERSION,
            "Authorization": self._auth(verb, resource_type,
                                        resource_link, date),
            "Content-Type": content_type,
            **(headers or {}),
        }
        req = urllib.request.Request(
            f"{self.endpoint}/{path}", method=verb,
            data=json.dumps(body).encode() if body is not None else None,
            headers=hdrs)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                raw = resp.read()
                return resp.status, json.loads(raw) if raw else None
        except urllib.error.HTTPError as exc:
            if exc.code in ok:
                raw = exc.read()
                return exc.code, json.loads(raw) if raw else None
            if exc.code == 409:
                raise DuplicateKeyError(
                    f"cosmos conflict on {path}") from exc
            if exc.code == 404 and notfound_ok:
                # Only reads/deletes may treat 404 as "absent" — a 404
                # on a WRITE (collection dropped externally) must raise,
                # not silently drop the document.
                return 404, None
            raise StorageError(
                f"cosmos {verb} {path} failed: HTTP {exc.code} "
                f"{exc.read()[:200].decode('utf-8', 'replace')}") from exc
        except (urllib.error.URLError, TimeoutError, OSError) as exc:
            raise StorageError(f"cosmos unreachable: {exc}") from exc
        except json.JSONDecodeError as exc:
            raise StorageError(f"cosmos returned non-JSON: {exc}") from exc

    # -- lifecycle ------------------------------------------------------

    def connect(self) -> None:
        if self._connected:
            return
        try:
            self._request("POST", "dbs", "", "dbs",
                          {"id": self.database}, ok=(201,))
        except DuplicateKeyError:
            pass
        self._connected = True

    def close(self) -> None:
        self._connected = False

    def _coll_link(self, collection: str) -> str:
        return f"dbs/{self.database}/colls/{collection}"

    def _ensure_coll(self, collection: str) -> None:
        if collection in self._known_colls:
            return
        self.connect()
        try:
            self._request(
                "POST", "colls", f"dbs/{self.database}",
                f"dbs/{self.database}/colls",
                {"id": collection,
                 "partitionKey": {"paths": ["/id"], "kind": "Hash"}},
                ok=(201,))
        except DuplicateKeyError:
            pass
        self._known_colls.add(collection)

    # -- id mapping -----------------------------------------------------

    @staticmethod
    def _check_id(doc_id: str) -> str:
        # Cosmos forbids / \ ? # in ids; anything else URL-quotes for
        # the resource path. Reject the forbidden set at write time so a
        # stored document is never unreachable by id.
        doc_id = str(doc_id)
        if not doc_id or any(c in doc_id for c in "/\\?#"):
            raise StorageError(f"invalid cosmos document id {doc_id!r}")
        return doc_id

    @staticmethod
    def _quote_id(doc_id: str) -> str:
        return urllib.parse.quote(str(doc_id), safe="")

    def _key(self, collection: str, doc: Mapping[str, Any]) -> str:
        pk = registry.primary_key(collection)
        doc_id = doc.get(pk)
        if not doc_id:
            raise DuplicateKeyError(
                f"document for {collection!r} missing primary key {pk!r}")
        if "id" in doc and str(doc["id"]) != str(doc_id):
            # 'id' is the wire-level primary key this driver derives
            # from the registry pk; a conflicting user field would be
            # silently clobbered on write and popped on read.
            raise StorageError(
                "'id' is reserved by the cosmos driver (it mirrors the "
                f"registry primary key); got id={doc['id']!r} vs "
                f"pk={doc_id!r}")
        return self._check_id(doc_id)

    #: Cosmos-injected system properties — stripped on read so stored
    #: documents round-trip byte-identical (user keys like ``_id`` and
    #: arbitrary underscore-prefixed fields survive).
    _SYSTEM_PROPS = frozenset(
        {"_rid", "_ts", "_self", "_etag", "_attachments"})

    @classmethod
    def _strip(cls, doc: dict | None) -> dict | None:
        if doc is None:
            return None
        return {k: v for k, v in doc.items()
                if k not in cls._SYSTEM_PROPS}

    def _pk_header(self, doc_id: str) -> dict[str, str]:
        return {"x-ms-documentdb-partitionkey": json.dumps([doc_id])}

    # -- DocumentStore contract ----------------------------------------

    def insert_document(self, collection, doc):
        self._ensure_coll(collection)
        doc_id = self._key(collection, doc)
        body = {**dict(doc), "id": doc_id}
        self._request("POST", "docs", self._coll_link(collection),
                      f"{self._coll_link(collection)}/docs", body,
                      headers=self._pk_header(doc_id), ok=(201,))
        return doc_id

    def upsert_document(self, collection, doc):
        self._ensure_coll(collection)
        doc_id = self._key(collection, doc)
        body = {**dict(doc), "id": doc_id}
        self._request("POST", "docs", self._coll_link(collection),
                      f"{self._coll_link(collection)}/docs", body,
                      headers={**self._pk_header(doc_id),
                               "x-ms-documentdb-is-upsert": "true"},
                      ok=(200, 201))
        return doc_id

    def get_document(self, collection, doc_id):
        self._ensure_coll(collection)
        link = (f"{self._coll_link(collection)}/docs/"
                f"{self._quote_id(doc_id)}")
        raw_link = f"{self._coll_link(collection)}/docs/{doc_id}"
        status, doc = self._request("GET", "docs", raw_link, link,
                                    headers=self._pk_header(str(doc_id)),
                                    notfound_ok=True)
        if status == 404 or doc is None:
            return None
        doc.pop("id", None)
        return self._strip(doc)

    def query_documents(self, collection, flt=None, *, limit=None,
                        skip=0, sort=None):
        self._ensure_coll(collection)
        where, params = translate_filter(flt)
        sql = "SELECT * FROM c"
        if where:
            sql += f" WHERE {where}"
        if sort:
            sql += " ORDER BY " + ", ".join(
                f"{sql_field(f)} {'DESC' if d < 0 else 'ASC'}"
                for f, d in sort)
        if skip or limit is not None:
            sql += (f" OFFSET {int(skip)} LIMIT "
                    f"{int(limit) if limit is not None else 2**31 - 1}")
        docs = self._query_all(collection, sql, params)
        for d in docs:
            d.pop("id", None)
        return [self._strip(d) for d in docs]

    def _query_all(self, collection: str, sql: str,
                   params: list[dict]) -> list[dict]:
        """Run a query following x-ms-continuation until exhausted —
        real Cosmos pages results (default ~100/page); reading one page
        silently truncates."""
        out: list[dict] = []
        continuation: str | None = None
        while True:
            headers = {"x-ms-documentdb-isquery": "true",
                       "x-ms-documentdb-query-enablecrosspartition":
                           "true"}
            if continuation:
                headers["x-ms-continuation"] = continuation
            date = formatdate(time.time(), usegmt=True)
            link = self._coll_link(collection)
            req = urllib.request.Request(
                f"{self.endpoint}/{link}/docs", method="POST",
                data=json.dumps({"query": sql,
                                 "parameters": params}).encode(),
                headers={
                    "x-ms-date": date,
                    "x-ms-version": self.API_VERSION,
                    "Authorization": self._auth("POST", "docs", link,
                                                date),
                    "Content-Type": "application/query+json",
                    **headers,
                })
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    page = json.loads(resp.read() or b"{}")
                    continuation = resp.headers.get("x-ms-continuation")
            except urllib.error.HTTPError as exc:
                raise StorageError(
                    f"cosmos query failed: HTTP {exc.code} "
                    f"{exc.read()[:200].decode('utf-8', 'replace')}"
                ) from exc
            except (urllib.error.URLError, TimeoutError, OSError) as exc:
                raise StorageError(f"cosmos unreachable: {exc}") from exc
            out.extend(page.get("Documents", []))
            if not continuation:
                return out

    def update_document(self, collection, doc_id, updates):
        # Optimistic concurrency: merge onto the CURRENT revision and
        # replace with If-Match on its _etag; a concurrent writer gets
        # 412 and we re-read — no lost updates (sqlite's atomic UPDATE
        # equivalent for a remote store).
        self._ensure_coll(collection)
        for _ in range(8):
            link = (f"{self._coll_link(collection)}/docs/"
                    f"{self._quote_id(doc_id)}")
            raw_link = f"{self._coll_link(collection)}/docs/{doc_id}"
            status, current = self._request(
                "GET", "docs", raw_link, link,
                headers=self._pk_header(str(doc_id)), notfound_ok=True)
            if status == 404 or current is None:
                return False
            etag = current.get("_etag", "")
            merged = self._strip(current)
            merged.pop("id", None)
            merged.update(dict(updates))
            body = {**merged, "id": str(doc_id)}
            try:
                self._request("PUT", "docs", raw_link, link, body,
                              headers={**self._pk_header(str(doc_id)),
                                       "If-Match": etag},
                              ok=(200,))
                return True
            except StorageError as exc:
                if "HTTP 412" not in str(exc):
                    raise
        raise StorageError(
            f"update_document lost the etag race 8 times for "
            f"{collection}/{doc_id}")

    def delete_document(self, collection, doc_id):
        self._ensure_coll(collection)
        link = (f"{self._coll_link(collection)}/docs/"
                f"{self._quote_id(doc_id)}")
        raw_link = f"{self._coll_link(collection)}/docs/{doc_id}"
        status, _ = self._request("DELETE", "docs", raw_link, link,
                                  headers=self._pk_header(str(doc_id)),
                                  ok=(204,), notfound_ok=True)
        return status == 204

    def delete_documents(self, collection, flt=None):
        n = 0
        for doc in self.query_documents(collection, flt):
            pk = registry.primary_key(collection)
            if self.delete_document(collection, str(doc.get(pk))):
                n += 1
        return n

    def count_documents(self, collection, flt=None):
        self._ensure_coll(collection)
        where, params = translate_filter(flt)
        sql = "SELECT VALUE COUNT(1) FROM c"
        if where:
            sql += f" WHERE {where}"
        pages = self._query_all(collection, sql, params)
        # VALUE COUNT(1) returns one scalar per page/partition; sum them
        return int(sum(int(v) for v in pages)) if pages else 0
