"""SQLite document store: the durable single-host driver.

One table per collection (``id TEXT PRIMARY KEY, doc TEXT`` JSON), WAL mode
for concurrent reader/writer services. Fills the durable-store role the
reference delegates to MongoDB (``mongo_document_store.py:33``) without an
external process — including its index story: the Mongo driver declares
per-collection indexes on the hot filter fields
(``mongo_document_store.py:33``); here the same fields get SQLite
*expression indexes* over ``json_extract(doc, '$.field')``, and the
Mongo-style filter subset compiles to SQL ``WHERE`` clauses that use them.
Queries the compiler can't express exactly (``$regex``, ``None`` inside
``$in`` lists, exotic paths) fall back to the shared Python matcher, so
semantics never change — only the plan does.

Known divergences from the Python matcher, both outside the pipeline's
data contract: (a) mixed-type range comparisons raise TypeError in Python
but exclude the row in SQL; (b) strings containing U+0000 are truncated
at the NUL by SQLite's json_extract (C-string semantics), so ``"a\\x00b"``
compares as ``"a"`` in SQL — no pipeline stage writes NULs into documents.
"""

from __future__ import annotations

import functools
import json
import pathlib
import re
import sqlite3
import threading
from typing import Any, Callable, Mapping, Sequence

from copilot_for_consensus_tpu.storage import registry
from copilot_for_consensus_tpu.storage.base import (
    DocumentStore,
    DuplicateKeyError,
    StorageContentionError,
    StorageError,
    matches_filter,
    sort_documents,
)


def _transient_locks(fn: Callable) -> Callable:
    """Translate sqlite lock contention (``SQLITE_BUSY``/``SQLITE_LOCKED``
    surfacing as ``OperationalError: database is locked`` past the busy
    timeout under concurrent writer services) into the retryable
    :class:`StorageContentionError`, so the service retry policy backs
    off and the lease/redelivery path applies instead of the envelope
    being classified as poison."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except sqlite3.OperationalError as exc:
            msg = str(exc).lower()
            if "locked" in msg or "busy" in msg:
                raise StorageContentionError(str(exc)) from exc
            raise

    return wrapper

_TABLE_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_PATH_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)*$")

# Hot filter/sort fields per collection → expression indexes, mirroring the
# role of the reference Mongo driver's per-collection index declarations
# (``mongo_document_store.py:33``). Extra entries are harmless; missing ones
# only cost a scan.
INDEX_FIELDS: dict[str, tuple[str, ...]] = {
    "archives": ("source_id", "status"),
    "messages": ("thread_id", "source_id", "archive_id", "status"),
    "threads": ("source_id", "status"),
    "chunks": ("thread_id", "source_id", "message_doc_id",
               "embedding_generated", "seq"),
    "summaries": ("thread_id", "source_id", "status"),
    "reports": ("thread_id", "summary_id", "status", "published_at"),
    "sources": ("enabled",),
}


def _ex(path: str) -> str:
    """The indexed extraction expression for a validated dotted path.
    (Primary-key paths never reach here: _compile_pk_condition maps them
    to the ``id`` PRIMARY KEY column first — a B-tree lookup instead of
    a full-table json_extract scan for the hot ``chunk_id: {"$in":
    [...]}`` queries every pipeline stage issues.)"""
    return f"json_extract(doc, '$.{path}')"


def _ty(path: str) -> str:
    return f"json_type(doc, '$.{path}')"


class _Incompatible(Exception):
    """Filter/sort shape the SQL compiler can't express exactly."""


def _compile_pk_condition(cond: Any, params: list) -> str | None:
    """Primary-key fast path onto the ``id`` column (B-tree lookup).
    Only string comparisons are safe — ``id`` holds ``str(doc_id)``
    while the JSON copy keeps the original type — so anything else
    returns None and takes the json_extract path."""
    if isinstance(cond, str):
        params.append(cond)
        return "id = ?"
    if isinstance(cond, Mapping) and cond and all(
            k in ("$in", "$nin", "$ne") for k in cond):
        clauses = []
        local: list = []
        for op, arg in cond.items():
            if op == "$ne":
                if not isinstance(arg, str):
                    return None
                local.append(arg)
                clauses.append("id != ?")
                continue
            vals = list(arg)
            if not all(isinstance(v, str) for v in vals):
                return None
            if not vals:
                # $in []: never matches; $nin []: pk always exists.
                clauses.append("0" if op == "$in" else "1")
                continue
            marks = ",".join("?" for _ in vals)
            local.extend(vals)
            clauses.append(f"id {'IN' if op == '$in' else 'NOT IN'} "
                           f"({marks})")
        params.extend(local)
        return "(" + " AND ".join(clauses) + ")"
    return None


def _compile_condition(path: str, cond: Any, params: list,
                       pk: str | None = None) -> str:
    if pk is not None and path == pk:
        fast = _compile_pk_condition(cond, params)
        if fast is not None:
            return fast
    if not _PATH_RE.match(path):
        raise _Incompatible(path)
    if isinstance(cond, Mapping) and any(k.startswith("$") for k in cond):
        clauses = []
        for op, arg in cond.items():
            if op == "$exists":
                clauses.append(f"{_ty(path)} IS " +
                               ("NOT NULL" if arg else "NULL"))
            elif op == "$ne":
                if arg is None:
                    clauses.append(f"({_ty(path)} IS NOT NULL "
                                   f"AND {_ty(path)} != 'null')")
                elif not isinstance(arg, (str, int, float, bool)):
                    raise _Incompatible(op)
                else:
                    params.append(arg)
                    clauses.append(f"({_ex(path)} IS NULL "
                                   f"OR {_ex(path)} != ?)")
            elif op in ("$in", "$nin"):
                vals = list(arg)
                if any(v is None for v in vals) or not all(
                        isinstance(v, (str, int, float, bool)) for v in vals):
                    raise _Incompatible(op)
                if not vals:
                    # Matcher: $in [] never matches; $nin [] matches any
                    # doc whose field exists ('NOT IN (NULL)' would be
                    # NULL → reject-all, so special-case both).
                    clauses.append("0" if op == "$in"
                                   else f"{_ty(path)} IS NOT NULL")
                    continue
                marks = ",".join("?" for _ in vals)
                params.extend(vals)
                if op == "$in":
                    clauses.append(f"{_ex(path)} IN ({marks})")
                else:
                    clauses.append(
                        f"({_ty(path)} IS NOT NULL AND ({_ty(path)}='null' "
                        f"OR {_ex(path)} NOT IN ({marks})))")
            elif op in ("$lt", "$lte", "$gt", "$gte"):
                if not isinstance(arg, (str, int, float)) or isinstance(
                        arg, bool):
                    raise _Incompatible(op)
                sql_op = {"$lt": "<", "$lte": "<=",
                          "$gt": ">", "$gte": ">="}[op]
                # Type guard: the Python matcher raises TypeError on a
                # str-vs-number comparison; SQL can't raise, so mixed-type
                # rows are excluded instead of silently type-ordered.
                # Python bools ARE ints, so they stay comparable to numbers.
                want = ("'text'" if isinstance(arg, str)
                        else "'integer','real','true','false'")
                params.append(arg)
                clauses.append(f"({_ty(path)} IN ({want}) "
                               f"AND {_ex(path)} {sql_op} ?)")
            else:  # $regex and anything unknown → Python matcher
                raise _Incompatible(op)
        return "(" + " AND ".join(clauses) + ")"
    # Plain equality.
    if cond is None:
        return f"{_ty(path)} = 'null'"
    if not isinstance(cond, (str, int, float, bool)):
        raise _Incompatible(type(cond).__name__)
    params.append(cond)
    return f"{_ex(path)} = ?"


def _compile_filter(flt: Mapping[str, Any] | None, params: list,
                    pk: str | None = None) -> str:
    """Compile the Mongo-subset filter to a WHERE expression with exactly the
    semantics of :func:`matches_filter`; raises _Incompatible otherwise."""
    if not flt:
        return "1"
    clauses = []
    for key, cond in flt.items():
        if key == "$or":
            subs = [_compile_filter(sub, params, pk) for sub in cond]
            clauses.append("(" + " OR ".join(subs or ["0"]) + ")")
        elif key == "$and":
            subs = [_compile_filter(sub, params, pk) for sub in cond]
            clauses.append("(" + " AND ".join(subs or ["1"]) + ")")
        elif key.startswith("$"):
            raise _Incompatible(key)
        else:
            clauses.append(_compile_condition(key, cond, params, pk))
    return "(" + " AND ".join(clauses) + ")"


def _compile_sort(sort: Sequence[tuple[str, int]] | None) -> str:
    """ORDER BY matching sort_documents: ascending puts None last,
    descending (full reverse) puts None first; rowid breaks ties in
    insertion order like Python's stable sort."""
    if not sort:
        # Match the fallback/memory stores' insertion order (rowid order);
        # without this, an index scan would return rows grouped by key.
        return " ORDER BY rowid ASC"
    terms = []
    for field_name, direction in sort:
        if not _PATH_RE.match(field_name):
            raise _Incompatible(field_name)
        d = "DESC" if direction < 0 else "ASC"
        terms.append(f"{_ex(field_name)} IS NULL {d}, {_ex(field_name)} {d}")
    return " ORDER BY " + ", ".join(terms) + ", rowid ASC"


class SQLiteDocumentStore(DocumentStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self.path = cfg.get("path", "var/documents.sqlite3")
        self._local = threading.local()
        self._known_tables: set[str] = set()
        self._lock = threading.Lock()

    # -- connection management (one sqlite connection per thread) ----------

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self.path != ":memory:":
                pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def _table(self, collection: str) -> str:
        if not _TABLE_RE.match(collection):
            raise StorageError(f"invalid collection name {collection!r}")
        table = f"docs_{collection}"
        if table not in self._known_tables:
            with self._lock:
                self._conn().execute(
                    f"CREATE TABLE IF NOT EXISTS {table} "
                    "(id TEXT PRIMARY KEY, doc TEXT NOT NULL)"
                )
                for field_name in INDEX_FIELDS.get(collection, ()):
                    self._conn().execute(
                        f"CREATE INDEX IF NOT EXISTS "
                        f"idx_{collection}_{field_name} ON {table} "
                        f"({_ex(field_name)})")
                self._conn().commit()
                self._known_tables.add(table)
        return table

    def _key(self, collection: str, doc: Mapping[str, Any]) -> str:
        pk = registry.primary_key(collection)
        doc_id = doc.get(pk)
        if not doc_id:
            raise DuplicateKeyError(
                f"document for {collection!r} missing primary key {pk!r}")
        return str(doc_id)

    # -- CRUD --------------------------------------------------------------

    @_transient_locks
    def insert_document(self, collection, doc):
        table = self._table(collection)
        doc_id = self._key(collection, doc)
        try:
            self._conn().execute(
                f"INSERT INTO {table} (id, doc) VALUES (?, ?)",
                (doc_id, json.dumps(dict(doc))),
            )
            self._conn().commit()
        except sqlite3.IntegrityError as exc:
            raise DuplicateKeyError(f"{collection}/{doc_id} exists") from exc
        return doc_id

    @_transient_locks
    def upsert_document(self, collection, doc):
        table = self._table(collection)
        doc_id = self._key(collection, doc)
        self._conn().execute(
            f"INSERT INTO {table} (id, doc) VALUES (?, ?) "
            "ON CONFLICT(id) DO UPDATE SET doc=excluded.doc",
            (doc_id, json.dumps(dict(doc))),
        )
        self._conn().commit()
        return doc_id

    @_transient_locks
    def get_document(self, collection, doc_id):
        table = self._table(collection)
        row = self._conn().execute(
            f"SELECT doc FROM {table} WHERE id=?", (str(doc_id),)
        ).fetchone()
        return json.loads(row[0]) if row else None

    # sqlite's bound-parameter ceiling (SQLITE_MAX_VARIABLE_NUMBER,
    # 999 in older builds): bulk IN()/executemany batches stay under it
    _BULK_CHUNK = 500

    @_transient_locks
    def get_documents(self, collection, doc_ids):
        """One ``IN (...)`` B-tree probe per 500 ids instead of one
        round-trip per id — the multi-get the batched stage hot paths
        (chunking/parsing waves) ride."""
        table = self._table(collection)
        ids = []
        seen: set[str] = set()
        for doc_id in doc_ids:
            key = str(doc_id)
            if key not in seen:
                seen.add(key)
                ids.append(key)
        out: dict[str, dict] = {}
        conn = self._conn()
        for start in range(0, len(ids), self._BULK_CHUNK):
            chunk = ids[start:start + self._BULK_CHUNK]
            marks = ",".join("?" for _ in chunk)
            for doc_id, raw in conn.execute(
                    f"SELECT id, doc FROM {table} WHERE id IN ({marks})",
                    chunk):
                out[doc_id] = json.loads(raw)
        return out

    @_transient_locks
    def insert_many(self, collection, docs, ignore_duplicates=True):
        """One transaction for the whole wave. With
        ``ignore_duplicates`` the insert is ``OR IGNORE`` (the
        dup-key-tolerant chunk-insert contract); without it the first
        duplicate raises :class:`DuplicateKeyError` and nothing from
        the batch commits."""
        table = self._table(collection)
        rows = [(self._key(collection, d), json.dumps(dict(d)))
                for d in docs]
        if not rows:
            return 0
        conn = self._conn()
        verb = "INSERT OR IGNORE" if ignore_duplicates else "INSERT"
        n = 0
        try:
            for start in range(0, len(rows), self._BULK_CHUNK):
                chunk = rows[start:start + self._BULK_CHUNK]
                cur = conn.executemany(
                    f"{verb} INTO {table} (id, doc) VALUES (?, ?)", chunk)
                # OR IGNORE: rowcount counts only rows actually inserted
                n += max(0, cur.rowcount)
        except sqlite3.IntegrityError as exc:
            conn.rollback()
            raise DuplicateKeyError(
                f"duplicate key in {collection} bulk insert") from exc
        conn.commit()
        return n

    @_transient_locks
    def update_documents(self, collection, doc_ids, updates):
        """Bulk same-fields merge in ONE transaction under the writer
        lock — the ``chunked: True`` flag-flip a wave of messages pays
        once instead of per message."""
        table = self._table(collection)
        ids = []
        seen: set[str] = set()
        for doc_id in doc_ids:
            key = str(doc_id)
            if key not in seen:
                seen.add(key)
                ids.append(key)
        if not ids:
            return 0
        fields = dict(updates)
        conn = self._conn()
        n = 0
        with self._lock:
            for start in range(0, len(ids), self._BULK_CHUNK):
                chunk = ids[start:start + self._BULK_CHUNK]
                marks = ",".join("?" for _ in chunk)
                rows = conn.execute(
                    f"SELECT id, doc FROM {table} WHERE id IN ({marks})",
                    chunk).fetchall()
                merged = []
                for doc_id, raw in rows:
                    doc = json.loads(raw)
                    doc.update(fields)
                    merged.append((json.dumps(doc), doc_id))
                if merged:
                    conn.executemany(
                        f"UPDATE {table} SET doc=? WHERE id=?", merged)
                    n += len(merged)
            conn.commit()
        return n

    def _iter_docs(self, collection):
        table = self._table(collection)
        for (raw,) in self._conn().execute(f"SELECT doc FROM {table}"):
            yield json.loads(raw)

    def _python_query(self, collection, flt, *, limit=None, skip=0,
                      sort=None):
        """Fallback path: full scan + the shared Python matcher — used
        for filter shapes the compiler can't express and for parameter
        values sqlite can't bind (lone surrogates in filter strings)."""
        docs = [d for d in self._iter_docs(collection)
                if matches_filter(d, flt)]
        sort_documents(docs, sort)
        if skip:
            docs = docs[skip:]
        if limit is not None:
            docs = docs[:limit]
        return docs

    @_transient_locks
    def query_documents(self, collection, flt=None, *, limit=None, skip=0,
                        sort: Sequence[tuple[str, int]] | None = None):
        table = self._table(collection)
        try:
            params: list = []
            where = _compile_filter(flt, params,
                                    registry.primary_key(collection))
            order = _compile_sort(sort)
        except _Incompatible:
            return self._python_query(collection, flt, limit=limit,
                                      skip=skip, sort=sort)
        sql = f"SELECT doc FROM {table} WHERE {where}{order}"
        if limit is not None or skip:
            sql += " LIMIT ? OFFSET ?"
            params.extend([-1 if limit is None else limit, skip])
        try:
            return [json.loads(raw) for (raw,)
                    in self._conn().execute(sql, params)]
        except UnicodeEncodeError:
            return self._python_query(collection, flt, limit=limit,
                                      skip=skip, sort=sort)

    @_transient_locks
    def update_document(self, collection, doc_id, updates):
        table = self._table(collection)
        conn = self._conn()
        with self._lock:
            row = conn.execute(
                f"SELECT doc FROM {table} WHERE id=?", (str(doc_id),)
            ).fetchone()
            if row is None:
                return False
            doc = json.loads(row[0])
            doc.update(dict(updates))
            conn.execute(
                f"UPDATE {table} SET doc=? WHERE id=?",
                (json.dumps(doc), str(doc_id)),
            )
            conn.commit()
            return True

    @_transient_locks
    def delete_document(self, collection, doc_id):
        table = self._table(collection)
        cur = self._conn().execute(
            f"DELETE FROM {table} WHERE id=?", (str(doc_id),))
        self._conn().commit()
        return cur.rowcount > 0

    def _python_delete(self, collection, flt):
        table = self._table(collection)
        ids = [str(d[registry.primary_key(collection)])
               for d in self._iter_docs(collection)
               if matches_filter(d, flt)]
        for doc_id in ids:
            self._conn().execute(
                f"DELETE FROM {table} WHERE id=?", (doc_id,))
        self._conn().commit()
        return len(ids)

    @_transient_locks
    def delete_documents(self, collection, flt=None):
        table = self._table(collection)
        try:
            params: list = []
            where = _compile_filter(flt, params,
                                    registry.primary_key(collection))
        except _Incompatible:
            return self._python_delete(collection, flt)
        try:
            cur = self._conn().execute(
                f"DELETE FROM {table} WHERE {where}", params)
        except UnicodeEncodeError:
            return self._python_delete(collection, flt)
        self._conn().commit()
        return cur.rowcount

    @_transient_locks
    def count_documents(self, collection, flt=None):
        table = self._table(collection)
        try:
            params: list = []
            where = _compile_filter(flt, params,
                                    registry.primary_key(collection))
        except _Incompatible:
            return sum(1 for d in self._iter_docs(collection)
                       if matches_filter(d, flt))
        try:
            return self._conn().execute(
                f"SELECT COUNT(*) FROM {table} WHERE {where}",
                params).fetchone()[0]
        except UnicodeEncodeError:
            return sum(1 for d in self._iter_docs(collection)
                       if matches_filter(d, flt))
