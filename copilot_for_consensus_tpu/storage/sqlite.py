"""SQLite document store: the durable single-host driver.

One table per collection (``id TEXT PRIMARY KEY, doc TEXT`` JSON), WAL mode
for concurrent reader/writer services, Mongo-style filters evaluated by the
shared matcher. Fills the durable-store role the reference delegates to
MongoDB (``mongo_document_store.py:33``) without an external process.
"""

from __future__ import annotations

import json
import pathlib
import re
import sqlite3
import threading
from typing import Any, Mapping, Sequence

from copilot_for_consensus_tpu.storage import registry
from copilot_for_consensus_tpu.storage.base import (
    DocumentStore,
    DuplicateKeyError,
    StorageError,
    matches_filter,
    sort_documents,
)

_TABLE_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


class SQLiteDocumentStore(DocumentStore):
    def __init__(self, config: Any = None):
        cfg = dict(config or {})
        self.path = cfg.get("path", "var/documents.sqlite3")
        self._local = threading.local()
        self._known_tables: set[str] = set()
        self._lock = threading.Lock()

    # -- connection management (one sqlite connection per thread) ----------

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self.path != ":memory:":
                pathlib.Path(self.path).parent.mkdir(parents=True, exist_ok=True)
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def _table(self, collection: str) -> str:
        if not _TABLE_RE.match(collection):
            raise StorageError(f"invalid collection name {collection!r}")
        table = f"docs_{collection}"
        if table not in self._known_tables:
            with self._lock:
                self._conn().execute(
                    f"CREATE TABLE IF NOT EXISTS {table} "
                    "(id TEXT PRIMARY KEY, doc TEXT NOT NULL)"
                )
                self._conn().commit()
                self._known_tables.add(table)
        return table

    def _key(self, collection: str, doc: Mapping[str, Any]) -> str:
        pk = registry.primary_key(collection)
        doc_id = doc.get(pk)
        if not doc_id:
            raise DuplicateKeyError(
                f"document for {collection!r} missing primary key {pk!r}")
        return str(doc_id)

    # -- CRUD --------------------------------------------------------------

    def insert_document(self, collection, doc):
        table = self._table(collection)
        doc_id = self._key(collection, doc)
        try:
            self._conn().execute(
                f"INSERT INTO {table} (id, doc) VALUES (?, ?)",
                (doc_id, json.dumps(dict(doc))),
            )
            self._conn().commit()
        except sqlite3.IntegrityError as exc:
            raise DuplicateKeyError(f"{collection}/{doc_id} exists") from exc
        return doc_id

    def upsert_document(self, collection, doc):
        table = self._table(collection)
        doc_id = self._key(collection, doc)
        self._conn().execute(
            f"INSERT INTO {table} (id, doc) VALUES (?, ?) "
            "ON CONFLICT(id) DO UPDATE SET doc=excluded.doc",
            (doc_id, json.dumps(dict(doc))),
        )
        self._conn().commit()
        return doc_id

    def get_document(self, collection, doc_id):
        table = self._table(collection)
        row = self._conn().execute(
            f"SELECT doc FROM {table} WHERE id=?", (str(doc_id),)
        ).fetchone()
        return json.loads(row[0]) if row else None

    def _iter_docs(self, collection):
        table = self._table(collection)
        for (raw,) in self._conn().execute(f"SELECT doc FROM {table}"):
            yield json.loads(raw)

    def query_documents(self, collection, flt=None, *, limit=None, skip=0,
                        sort: Sequence[tuple[str, int]] | None = None):
        docs = [d for d in self._iter_docs(collection) if matches_filter(d, flt)]
        sort_documents(docs, sort)
        if skip:
            docs = docs[skip:]
        if limit is not None:
            docs = docs[:limit]
        return docs

    def update_document(self, collection, doc_id, updates):
        table = self._table(collection)
        conn = self._conn()
        with self._lock:
            row = conn.execute(
                f"SELECT doc FROM {table} WHERE id=?", (str(doc_id),)
            ).fetchone()
            if row is None:
                return False
            doc = json.loads(row[0])
            doc.update(dict(updates))
            conn.execute(
                f"UPDATE {table} SET doc=? WHERE id=?",
                (json.dumps(doc), str(doc_id)),
            )
            conn.commit()
            return True

    def delete_document(self, collection, doc_id):
        table = self._table(collection)
        cur = self._conn().execute(
            f"DELETE FROM {table} WHERE id=?", (str(doc_id),))
        self._conn().commit()
        return cur.rowcount > 0

    def delete_documents(self, collection, flt=None):
        table = self._table(collection)
        if not flt:
            cur = self._conn().execute(f"DELETE FROM {table}")
            self._conn().commit()
            return cur.rowcount
        ids = [str(d[registry.primary_key(collection)])
               for d in self._iter_docs(collection) if matches_filter(d, flt)]
        for doc_id in ids:
            self._conn().execute(
                f"DELETE FROM {table} WHERE id=?", (doc_id,))
        self._conn().commit()
        return len(ids)

    def count_documents(self, collection, flt=None):
        table = self._table(collection)
        if not flt:
            return self._conn().execute(
                f"SELECT COUNT(*) FROM {table}").fetchone()[0]
        return sum(1 for d in self._iter_docs(collection)
                   if matches_filter(d, flt))
