"""Collection → schema + primary-key registry, loaded from
``schemas/documents/collections.config.json`` (parity with the reference's
``copilot_storage/schema_registry.py``)."""

from __future__ import annotations

import functools
import json
from typing import Any

from copilot_for_consensus_tpu.core.validation import SCHEMA_ROOT


@functools.lru_cache(maxsize=1)
def collection_registry() -> dict[str, dict[str, Any]]:
    path = SCHEMA_ROOT / "documents" / "collections.config.json"
    return json.loads(path.read_text())["collections"]


def primary_key(collection: str) -> str:
    reg = collection_registry()
    if collection in reg:
        return reg[collection]["primary_key"]
    return "_id"


def schema_name(collection: str) -> str | None:
    reg = collection_registry()
    if collection in reg:
        return reg[collection]["schema"]
    return None


KNOWN_COLLECTIONS = tuple(
    json.loads((SCHEMA_ROOT / "documents" / "collections.config.json").read_text())
    ["collections"]
)
