"""Schema-validating document store wrapper (parity with the reference's
``copilot_storage/validating_document_store.py:35``): every insert/upsert is
validated against the collection's schema from the registry; unknown
collections pass through unvalidated."""

from __future__ import annotations

from typing import Any, Mapping

from copilot_for_consensus_tpu.core.validation import (
    FileSchemaProvider,
    validate_json,
)
from copilot_for_consensus_tpu.storage import registry
from copilot_for_consensus_tpu.storage.base import DocumentStore


class ValidatingDocumentStore(DocumentStore):
    def __init__(self, inner: DocumentStore,
                 provider: FileSchemaProvider | None = None):
        self.inner = inner
        self.provider = provider

    def _validate(self, collection: str, doc: Mapping[str, Any]) -> None:
        name = registry.schema_name(collection)
        if name is not None:
            validate_json(doc, name, self.provider)

    def connect(self):
        self.inner.connect()

    def close(self):
        self.inner.close()

    def insert_document(self, collection, doc):
        self._validate(collection, doc)
        return self.inner.insert_document(collection, doc)

    def upsert_document(self, collection, doc):
        self._validate(collection, doc)
        return self.inner.upsert_document(collection, doc)

    def get_document(self, collection, doc_id):
        return self.inner.get_document(collection, doc_id)

    def query_documents(self, collection, flt=None, **kwargs):
        return self.inner.query_documents(collection, flt, **kwargs)

    def update_document(self, collection, doc_id, updates):
        # Merged docs are re-validated only when the collection is known and
        # the update could break required fields; cheap full check:
        current = self.inner.get_document(collection, doc_id)
        if current is not None:
            merged = {**current, **dict(updates)}
            self._validate(collection, merged)
        return self.inner.update_document(collection, doc_id, updates)

    def get_documents(self, collection, doc_ids):
        # Explicit: the base class inherits a concrete loop default, so
        # without this the wrapper would shadow the inner driver's
        # one-round-trip multi-get (the race-wrapper-shadow bug class).
        return self.inner.get_documents(collection, doc_ids)

    def insert_many(self, collection, docs, ignore_duplicates=True):
        docs = [dict(d) for d in docs]
        for doc in docs:
            self._validate(collection, doc)
        return self.inner.insert_many(collection, docs,
                                      ignore_duplicates)

    def update_documents(self, collection, doc_ids, updates):
        fields = dict(updates)
        current = self.inner.get_documents(collection, doc_ids)
        for doc in current.values():
            self._validate(collection, {**doc, **fields})
        return self.inner.update_documents(collection, doc_ids, fields)

    def delete_document(self, collection, doc_id):
        return self.inner.delete_document(collection, doc_id)

    def delete_documents(self, collection, flt=None):
        return self.inner.delete_documents(collection, flt)

    def count_documents(self, collection, flt=None):
        return self.inner.count_documents(collection, flt)

    def __getattr__(self, name):
        return getattr(self.inner, name)
