"""Document store driver registration + create_document_store."""

from __future__ import annotations

from typing import Any

from copilot_for_consensus_tpu.core.factory import register_driver
from copilot_for_consensus_tpu.storage.memory import InMemoryDocumentStore
from copilot_for_consensus_tpu.storage.sqlite import SQLiteDocumentStore
from copilot_for_consensus_tpu.storage.validating import ValidatingDocumentStore


def create_document_store(config: Any = None, validate: bool = True):
    cfg = dict(config or {})
    driver = cfg.get("driver", "memory")
    if driver == "memory":
        store = InMemoryDocumentStore(cfg)
    elif driver == "sqlite":
        store = SQLiteDocumentStore(cfg)
    elif driver == "azure_cosmos":
        from copilot_for_consensus_tpu.storage.azure_cosmos import (
            AzureCosmosDocumentStore,
        )

        store = AzureCosmosDocumentStore(
            account=cfg.get("account", ""),
            master_key=cfg.get("master_key", ""),
            database=cfg.get("database", "copilot"),
            endpoint=cfg.get("endpoint", "") or "")
    else:
        raise ValueError(f"unknown document_store driver {driver!r}")
    return ValidatingDocumentStore(store) if validate else store


for _name in ("memory", "sqlite", "azure_cosmos"):
    register_driver("document_store", _name, create_document_store)
