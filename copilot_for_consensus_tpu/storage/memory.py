"""In-memory document store (tests + single-process pipeline runs)."""

from __future__ import annotations

import copy
import threading
from typing import Any, Mapping, Sequence

from copilot_for_consensus_tpu.storage import registry
from copilot_for_consensus_tpu.storage.base import (
    DocumentStore,
    DuplicateKeyError,
    matches_filter,
    sort_documents,
)


class InMemoryDocumentStore(DocumentStore):
    def __init__(self, config: Any = None):
        self._collections: dict[str, dict[str, dict]] = {}
        self._lock = threading.RLock()

    def _coll(self, name: str) -> dict[str, dict]:
        return self._collections.setdefault(name, {})

    def _key(self, collection: str, doc: Mapping[str, Any]) -> str:
        pk = registry.primary_key(collection)
        doc_id = doc.get(pk)
        if not doc_id:
            raise DuplicateKeyError(
                f"document for {collection!r} missing primary key {pk!r}")
        return str(doc_id)

    def insert_document(self, collection, doc):
        with self._lock:
            coll = self._coll(collection)
            doc_id = self._key(collection, doc)
            if doc_id in coll:
                raise DuplicateKeyError(f"{collection}/{doc_id} exists")
            coll[doc_id] = copy.deepcopy(dict(doc))
            return doc_id

    def upsert_document(self, collection, doc):
        with self._lock:
            doc_id = self._key(collection, doc)
            self._coll(collection)[doc_id] = copy.deepcopy(dict(doc))
            return doc_id

    def get_document(self, collection, doc_id):
        with self._lock:
            doc = self._coll(collection).get(str(doc_id))
            return copy.deepcopy(doc) if doc is not None else None

    def query_documents(self, collection, flt=None, *, limit=None, skip=0,
                        sort: Sequence[tuple[str, int]] | None = None):
        with self._lock:
            docs = [copy.deepcopy(d) for d in self._coll(collection).values()
                    if matches_filter(d, flt)]
        sort_documents(docs, sort)
        if skip:
            docs = docs[skip:]
        if limit is not None:
            docs = docs[:limit]
        return docs

    def update_document(self, collection, doc_id, updates):
        with self._lock:
            coll = self._coll(collection)
            doc = coll.get(str(doc_id))
            if doc is None:
                return False
            doc.update(copy.deepcopy(dict(updates)))
            return True

    def get_documents(self, collection, doc_ids):
        # one lock acquisition for the whole wave (the batched hot
        # paths' multi-get), instead of one per id
        with self._lock:
            coll = self._coll(collection)
            out = {}
            for doc_id in doc_ids:
                key = str(doc_id)
                if key in out:
                    continue
                doc = coll.get(key)
                if doc is not None:
                    out[key] = copy.deepcopy(doc)
            return out

    def update_documents(self, collection, doc_ids, updates):
        with self._lock:
            coll = self._coll(collection)
            n = 0
            seen: set[str] = set()
            fields = copy.deepcopy(dict(updates))
            for doc_id in doc_ids:
                key = str(doc_id)
                if key in seen:
                    continue
                seen.add(key)
                doc = coll.get(key)
                if doc is not None:
                    doc.update(copy.deepcopy(fields))
                    n += 1
            return n

    def delete_document(self, collection, doc_id):
        with self._lock:
            return self._coll(collection).pop(str(doc_id), None) is not None

    def delete_documents(self, collection, flt=None):
        with self._lock:
            coll = self._coll(collection)
            to_delete = [k for k, d in coll.items() if matches_filter(d, flt)]
            for k in to_delete:
                del coll[k]
            return len(to_delete)

    def count_documents(self, collection, flt=None):
        with self._lock:
            if not flt:
                return len(self._coll(collection))
            return sum(1 for d in self._coll(collection).values()
                       if matches_filter(d, flt))
