"""Document store abstraction over the 6 pipeline collections.

Capability parity with the reference's ``copilot_storage`` package
(ABC ``document_store.py:40``; Mongo/Cosmos/InMemory drivers; validating
wrapper; collection→schema registry — SURVEY.md §2.1). Drivers here:

* ``memory`` — dict-backed, for tests and the single-process runner;
* ``sqlite`` — durable single-host store on stdlib sqlite3 (WAL mode), the
  default persistent driver (the environment bans new services; a Mongo
  driver slot exists for when pymongo is present).

The store is the pipeline's durable state machine (SURVEY.md §5
"Checkpoint / resume"): per-document status flags + content-addressed ids
make every stage resumable and idempotent.
"""

from copilot_for_consensus_tpu.storage.base import (
    DocumentStore,
    DuplicateKeyError,
    StorageError,
    matches_filter,
)
from copilot_for_consensus_tpu.storage.memory import InMemoryDocumentStore
from copilot_for_consensus_tpu.storage.sqlite import SQLiteDocumentStore
from copilot_for_consensus_tpu.storage.validating import ValidatingDocumentStore
from copilot_for_consensus_tpu.storage.factory import create_document_store

__all__ = [
    "DocumentStore",
    "DuplicateKeyError",
    "StorageError",
    "matches_filter",
    "InMemoryDocumentStore",
    "SQLiteDocumentStore",
    "ValidatingDocumentStore",
    "create_document_store",
]
